"""Mid-stream fault tolerance (ISSUE 9): graceful drain, decode-resume
after worker loss, retry shaping, deadlines, and the deterministic
fault-injection layer.

The headline invariant: however a worker dies mid-stream — abrupt kill,
graceful drain with live migration, drain whose migration itself fails,
engine step crash — the client-observed token stream is EXACTLY-ONCE
(no gap, no duplicate) and byte-identical to the undisturbed run, greedy
and seeded-sampled, prefix cache on and off.

Chaos here is in-process and deterministic: a `PartitionableBus` facade
silences one worker the way SIGKILL does (publishes vanish, the
heartbeat key stops refreshing), drains are invoked directly, and every
injected failure goes through gridllm_tpu/faults.py so the scenario is a
pure function of its seed. The RESP-broker rolling-restart smoke (slow)
adds a real broker between the parties.
"""

from __future__ import annotations

import asyncio
import time
import uuid

import pytest

from gridllm_tpu import faults
from gridllm_tpu.bus import InMemoryBus
from gridllm_tpu.engine import EngineConfig, InferenceEngine
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.config import SchedulerConfig, WorkerConfig
from gridllm_tpu.utils.types import InferenceRequest, JobAssignment
from gridllm_tpu.worker.service import WorkerService

from .helpers import FakeWorker, fast_config

MODEL = "tiny-llama"
PROMPT = "the quick brown fox jumps over the lazy dog " * 2
N_PREDICT = 48   # long enough that mid-stream chaos lands mid-decode
CHAOS_TOKENS = 4  # decode progress (snapshot watermark) before chaos fires


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def make_engine(**kw) -> InferenceEngine:
    cfg = dict(
        model=MODEL, max_slots=2, page_size=8, num_pages=96,
        max_pages_per_slot=16, prefill_buckets=(16, 64, 128), seed=42,
        prefill_chunk=16,
    )
    cfg.update(kw)
    return InferenceEngine(EngineConfig(**cfg))


def ft_config(**kw) -> SchedulerConfig:
    """Sub-second liveness (a killed worker must be detected fast) but a
    generous job timeout (children pay first-compile costs)."""
    base = dict(
        worker_heartbeat_timeout_ms=600,
        worker_cleanup_interval_ms=100,
        connection_monitor_interval_ms=100,
        quick_disconnect_window_ms=400,
        orphan_assign_threshold_ms=200,
        job_timeout_ms=180_000,
        retry_attempts=3,
        retry_delay_ms=50,
        sweep_interval_ms=100,
    )
    base.update(kw)
    return SchedulerConfig(**base)


class PartitionableBus:
    """Per-worker facade over the shared in-memory bus. Flipping ``dead``
    is SIGKILL as the cluster sees it: every outbound publish/hset/
    heartbeat-key refresh vanishes, so the registry's liveness tiers see
    an abruptly dead worker — while the victim process (here: its tasks
    and engine thread) keeps running, exactly like a real partition."""

    def __init__(self, inner):
        self._inner = inner
        self.dead = False

    def __getattr__(self, name):
        return getattr(self._inner, name)

    async def publish(self, channel: str, message: str):
        if self.dead:
            return 0
        return await self._inner.publish(channel, message)

    async def hset(self, key: str, field: str, value: str):
        if self.dead:
            return
        return await self._inner.hset(key, field, value)

    async def set_with_expiry(self, key: str, value: str, ttl_s: float):
        if self.dead:
            return
        return await self._inner.set_with_expiry(key, value, ttl_s)


class Fleet:
    """In-process fleet: scheduler + N real-engine unified workers, each
    behind its own PartitionableBus so one can be killed mid-stream."""

    def __init__(self, n: int = 1, snap_every: int = 2,
                 cfg: SchedulerConfig | None = None):
        self.n = n
        self.snap_every = snap_every
        self.cfg = cfg or ft_config()
        self.workers: list[WorkerService] = []

    async def __aenter__(self) -> "Fleet":
        self.bus = InMemoryBus()
        await self.bus.connect()
        self.registry = WorkerRegistry(self.bus, self.cfg)
        self.scheduler = JobScheduler(self.bus, self.registry, self.cfg)
        await self.registry.initialize()
        await self.scheduler.initialize()
        for i in range(self.n):
            svc = WorkerService(
                PartitionableBus(self.bus), {MODEL: make_engine()},
                WorkerConfig(worker_id=f"ft-w{i}",
                             heartbeat_interval_ms=150),
                stream_flush_ms=5)
            svc._snap_every = self.snap_every
            await svc.start()
            self.workers.append(svc)
        await asyncio.sleep(0.4)  # first heartbeats land
        return self

    async def __aexit__(self, *exc) -> None:
        for svc in self.workers:
            await svc.stop(announce=False)
        await self.scheduler.shutdown()
        await self.registry.shutdown()
        await self.bus.disconnect()

    def resume_count(self, event: str) -> int:
        return int(self.scheduler._resume_total.value(event=event))

    def job_count(self, event: str) -> int:
        return int(self.scheduler._jobs_total.value(event=event))

    def worker_for(self, job_id: str) -> WorkerService:
        wid = self.scheduler.active_jobs[job_id].workerId
        return next(w for w in self.workers if w.worker_id == wid)

    async def wait_decode_progress(self, job_id: str,
                                   min_tokens: int = CHAOS_TOKENS) -> None:
        """Block until the job's snapshot watermark covers min_tokens —
        a DETERMINISTIC mid-decode point (client-observed chars lag the
        engine arbitrarily under load, so they cannot time chaos)."""
        for _ in range(9000):
            snap = self.scheduler._resume_snap.get(job_id)
            if snap is not None and len(snap["tokens"]) >= min_tokens:
                return
            await asyncio.sleep(0.01)
        raise AssertionError("decode never reached the chaos point")

    async def run(self, n: int = N_PREDICT, chaos=None,
                  chaos_wait: bool = True, **opts):
        """One streaming request. ``chaos(job_id)`` fires once, as soon
        as the decode's snapshot watermark shows mid-stream progress
        (``chaos_wait=False`` hands the timing to the callback)."""
        chunks: list[str] = []

        async def on_chunk(c) -> None:
            chunks.append(c.response)

        req = InferenceRequest(
            id=f"ft-{uuid.uuid4().hex[:8]}", model=MODEL, prompt=PROMPT,
            stream=True,
            options={"temperature": 0, "num_predict": n, **opts},
            metadata={"requestType": "inference"})
        task = asyncio.create_task(self.scheduler.submit_streaming_job(
            req, on_chunk, timeout_ms=120_000))
        if chaos is not None:
            if chaos_wait:
                await self.wait_decode_progress(req.id)
            await chaos(req.id)
        result = await task
        text = "".join(chunks)
        if result.success and result.response is not None:
            # self-consistency: the delivered stream IS the final text —
            # no splice, no gap, no duplicate, whatever chaos happened
            assert text == (result.response.response or ""), \
                "client stream diverged from the final response text"
        return text, result


async def reference_run(n: int = N_PREDICT, **opts) -> tuple[str, int]:
    """The undisturbed run every chaos stream must byte-match."""
    async with Fleet(1) as ref:
        text, res = await ref.run(n=n, **opts)
        assert res.success, res.error
        return text, int(res.response.eval_count or 0)


# ------------------------------------------------------------ faults.py


def test_fault_spec_parse_rejects_garbage():
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.parse_spec("bus.pubish=1", 0)
    with pytest.raises(ValueError, match="expected site=value"):
        faults.parse_spec("bus.publish", 0)
    with pytest.raises(ValueError, match="probability"):
        faults.parse_spec("bus.publish=1.5", 0)
    with pytest.raises(ValueError, match="1-based"):
        faults.parse_spec("bus.publish=@0", 0)
    assert faults.parse_spec("", 0) == {}


def test_fault_decisions_are_a_pure_function_of_seed():
    """Same (seed, site) -> same decision sequence; different seed -> a
    different one. This is what makes chaos runs replayable."""

    def seq(seed: int, k: int = 64) -> list[bool]:
        faults.configure("engine.step=0.3", seed=seed)
        return [faults.check("engine.step") for _ in range(k)]

    a, b, c = seq(7), seq(7), seq(8)
    assert a == b
    assert a != c
    assert any(a) and not all(a)
    # sites draw from INDEPENDENT streams: one site's calls never
    # perturb another's decisions
    faults.configure("engine.step=0.3,bus.deliver=0.3", seed=7)
    mixed = []
    for _ in range(64):
        faults.check("bus.deliver")
        mixed.append(faults.check("engine.step"))
    assert mixed == a


def test_fault_call_index_modes():
    faults.configure("kvx.send=@3", seed=0)
    fired = [faults.check("kvx.send") for _ in range(6)]
    assert fired == [False, False, True, False, False, False]
    faults.configure("kvx.send=@3+", seed=0)
    fired = [faults.check("kvx.send") for _ in range(6)]
    assert fired == [False, False, True, True, True, True]
    # unconfigured sites never fire; inject() raises only when armed
    assert not faults.check("alloc.alloc")
    faults.configure("alloc.alloc=@1", seed=0)
    with pytest.raises(faults.InjectedFault):
        faults.inject("alloc.alloc")


def test_fault_env_spec_loads_lazily(monkeypatch):
    monkeypatch.setenv("GRIDLLM_FAULT_SPEC", "worker.heartbeat=@1")
    monkeypatch.setenv("GRIDLLM_FAULT_SEED", "3")
    faults.reset()  # forget, then lazily re-read the env on first check
    assert faults.check("worker.heartbeat")
    assert not faults.check("worker.heartbeat")


async def test_bus_sites_drop_and_raise():
    bus = InMemoryBus()
    await bus.connect()
    got: list[str] = []

    async def handler(_ch: str, raw: str) -> None:
        got.append(raw)

    await bus.subscribe("ft:chan", handler)
    try:
        faults.configure("bus.deliver=@1", seed=0)
        await bus.publish("ft:chan", "lost")
        await bus.publish("ft:chan", "kept")
        await bus.flush()
        assert got == ["kept"]  # first delivery dropped before the handler
        faults.configure("bus.publish=@1", seed=0)
        with pytest.raises(faults.InjectedFault):
            await bus.publish("ft:chan", "never-sent")
        await bus.publish("ft:chan", "after")
        await bus.flush()
        assert got == ["kept", "after"]
    finally:
        await bus.disconnect()


# ------------------------------------- retry shaping + request deadlines


def _bare_scheduler(cfg: SchedulerConfig) -> JobScheduler:
    """Uninitialized scheduler — enough for its pure helpers."""
    bus = InMemoryBus()
    return JobScheduler(bus, WorkerRegistry(bus, cfg), cfg)


def test_retry_backoff_doubles_and_caps():
    s = _bare_scheduler(SchedulerConfig(
        retry_delay_ms=100, retry_backoff_max_ms=500))
    assert [s._retry_backoff_ms(a) for a in range(5)] == [
        100.0, 200.0, 400.0, 500.0, 500.0]
    # cap never undershoots the base, and attempt never goes negative
    s2 = _bare_scheduler(SchedulerConfig(
        retry_delay_ms=100, retry_backoff_max_ms=10))
    assert s2._retry_backoff_ms(0) == 100.0
    assert s2._retry_backoff_ms(-1) == 100.0


def test_retry_budget_token_bucket():
    s = _bare_scheduler(SchedulerConfig(retry_budget_per_min=2))
    assert s._take_retry_token()
    assert s._take_retry_token()
    assert not s._take_retry_token()  # burnt — shed
    # refill is continuous: half a minute buys one token back
    s._retry_refill_t -= 30
    assert s._take_retry_token()
    assert not s._take_retry_token()
    # 0 = unlimited
    s0 = _bare_scheduler(SchedulerConfig(retry_budget_per_min=0))
    assert all(s0._take_retry_token() for _ in range(100))


async def test_retry_budget_exhaustion_sheds_to_immediate_failure():
    """A worker failing every attempt burns the one-token budget on its
    first retry; the second shed-checks, fails immediately with
    ``retry_budget_exhausted``, and never melts through the full ladder."""
    bus = InMemoryBus()
    await bus.connect()
    cfg = fast_config()
    cfg.retry_attempts = 5
    cfg.retry_delay_ms = 20
    cfg.retry_budget_per_min = 1
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    worker = FakeWorker(bus, "always-fails", ["m1"], fail_times=99)
    await worker.start()
    try:
        req = InferenceRequest(id="budget-1", model="m1", prompt="x")
        result = await scheduler.submit_and_wait(req, timeout_ms=10_000)
        assert not result.success
        assert result.error.startswith("retry_budget_exhausted")
        assert not result.retryable
        assert int(scheduler._jobs_total.value(event="retried")) == 1
        assert int(scheduler._jobs_total.value(
            event="retry_budget_exhausted")) == 1
    finally:
        await worker.stop(announce=False)
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()


def test_deadline_for_class_overrides():
    s = _bare_scheduler(SchedulerConfig(
        request_deadline_ms=60_000,
        request_deadline_classes={"batch": 1_000}))
    batch = InferenceRequest(id="d1", model="m", prompt="x")
    interactive = InferenceRequest(id="d2", model="m", prompt="x",
                                   stream=True)
    assert s._deadline_for(batch) == 1_000
    assert s._deadline_for(interactive) == 60_000


async def test_queued_job_past_deadline_is_shed_with_504():
    """The only model owner is saturated, so the job queues; it crosses
    its deadline, the sweep's dispatch pass sheds it with
    ``deadline_exceeded``, and the gateway maps the failure to a
    structured HTTP 504."""
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.gateway.app import create_app
    from gridllm_tpu.utils.config import Config

    bus = InMemoryBus()
    await bus.connect()
    cfg = fast_config()
    cfg.request_deadline_ms = 300
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    # owns the model (the gateway's availability check passes) but
    # reports over-capacity — the job stays queued until the deadline
    # sheds it
    worker = FakeWorker(bus, "saturated", ["m1"], max_concurrent=1)
    worker.current_jobs = 5
    await worker.start()
    config = Config()
    config.scheduler = cfg
    app = create_app(bus, registry, scheduler, config)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        t0 = time.monotonic()
        resp = await client.post("/ollama/api/generate", json={
            "model": "m1", "prompt": "x", "stream": False})
        elapsed = time.monotonic() - t0
        assert resp.status == 504
        body = await resp.json()
        assert body["error"]["code"] == "DEADLINE_EXCEEDED"
        # shed at the deadline, NOT at the 5 s job timeout
        assert elapsed < 3.0
        assert int(scheduler._jobs_total.value(
            event="deadline_exceeded")) == 1
        assert scheduler.get_job_queue() == []
        assert scheduler.tracer.active_count() == 0
    finally:
        await client.close()
        await worker.stop(announce=False)
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()


# ------------------------------------- requeue hygiene (satellite 6)


def test_snapshot_merge_is_monotonic():
    """Late/short/garbage snapshot deliveries never roll the watermark
    back — the stored snapshot only grows."""
    s = _bare_scheduler(SchedulerConfig())
    s._merge_snapshot("j1", {"tokens": [1, 2, 3], "seed": 7})
    s._merge_snapshot("j1", {"tokens": [9], "seed": 8})          # shorter
    s._merge_snapshot("j1", {"tokens": []})                       # empty
    s._merge_snapshot("j1", {"tokens": ["x"]})                    # garbage
    s._merge_snapshot("j1", {})                                   # missing
    assert s._resume_snap["j1"] == {"tokens": [1, 2, 3], "seed": 7}
    s._merge_snapshot("j1", {"tokens": [1, 2, 3, 4], "seed": 7})  # longer
    assert s._resume_snap["j1"]["tokens"] == [1, 2, 3, 4]


async def test_orphan_requeue_preserves_resume_and_strips_disagg():
    """Satellite 6: orphan-requeue strips the stale disagg plan (fresh
    dispatch replans) but must NOT drop the resume watermark — the
    replacement continues the decode instead of restarting it."""
    bus = InMemoryBus()
    await bus.connect()
    cfg = ft_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    try:
        req = InferenceRequest(
            id="orph-1", model=MODEL, prompt="x",
            metadata={"disagg": {"decodeWorkerId": "d1"},
                      "disaggPhase": "prefill"})
        assignment = JobAssignment(jobId="orph-1", workerId="w-dead",
                                   request=req, timeout=60_000)
        scheduler.active_jobs["orph-1"] = assignment
        scheduler._merge_snapshot("orph-1", {"tokens": [1, 2, 3],
                                             "seed": 7})
        scheduler._stream_chars["orph-1"] = 11
        await scheduler._orphan_job(assignment, reason="orphan_sweep")
        queued = scheduler.get_job_queue()
        assert [r.id for r in queued] == ["orph-1"]
        md = queued[0].metadata
        assert "disagg" not in md and "disaggPhase" not in md
        assert md["resume"] == {"tokens": [1, 2, 3], "seed": 7,
                                "sentChars": 11}
        assert int(scheduler._resume_total.value(event="stamped")) == 1
    finally:
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()


# ------------------------------------------ exactly-once chaos streams


async def test_kill_worker_mid_stream_greedy_exactly_once():
    """THE acceptance criterion: SIGKILL-equivalent loss of the serving
    worker mid-decode; the replacement resumes from the snapshot
    watermark and the client stream is byte-identical to the undisturbed
    greedy run — no gap, no duplicate, same eval_count."""
    text_ref, evals_ref = await reference_run()
    async with Fleet(2) as f:

        async def kill(job_id: str) -> None:
            victim = f.worker_for(job_id)
            victim.bus.dead = True  # type: ignore[attr-defined]

        text, res = await f.run(chaos=kill)
        assert res.success, res.error
        assert text == text_ref
        assert int(res.response.eval_count or 0) == evals_ref
        assert f.job_count("orphaned") >= 1
        assert f.resume_count("stamped") >= 1
        # the job finished on the surviving worker
        survivor = next(w for w in f.workers
                        if not w.bus.dead)  # type: ignore[attr-defined]
        assert res.workerId == survivor.worker_id
        assert f.scheduler.tracer.active_count() == 0


async def test_kill_worker_mid_stream_seeded_sampled_cache_off(monkeypatch):
    """Seeded-sampled resume with the prefix cache OFF: the snapshot's
    (seed, step) chain — not cached pages — is what makes the resumed
    stream byte-match. Spec decoding is disabled here because its
    rejection-sampling draws are not replayable by a direct draw: a
    spec-on sampled resume is distribution-correct (the tentpole's
    guarantee), byte-identical only without speculation."""
    monkeypatch.setenv("GRIDLLM_PREFIX_CACHE", "0")
    monkeypatch.setenv("GRIDLLM_SPEC_DECODE", "0")
    opts = dict(temperature=0.9, seed=1234)
    text_ref, evals_ref = await reference_run(**opts)
    async with Fleet(2) as f:

        async def kill(job_id: str) -> None:
            f.worker_for(job_id).bus.dead = True  # type: ignore[attr-defined]

        text, res = await f.run(chaos=kill, **opts)
        assert res.success, res.error
        assert text == text_ref
        assert int(res.response.eval_count or 0) == evals_ref
        assert f.resume_count("stamped") >= 1


async def test_kill_before_first_snapshot_unseeded_sampled_no_splice(
        monkeypatch):
    """A sampled request with NO client seed dies before its first
    token snapshot. The worker's seed-only watermark (published at
    generation start) makes the retry replay the SAME resolved seed, so
    the restarted-from-zero regeneration is byte-identical and the
    gateway's overlap trim cannot splice two divergent samples — the
    delivered stream must equal the final response text exactly."""
    monkeypatch.setenv("GRIDLLM_SPEC_DECODE", "0")
    # snap_every so large that NO token snapshot ever publishes: the
    # seed-only watermark is all the scheduler has when the kill lands
    async with Fleet(2, snap_every=10_000) as f:

        async def kill_at_seed_watermark(job_id: str) -> None:
            # the seed-only entry exists as soon as generation starts —
            # kill in the pre-first-token-snapshot window
            await f.wait_decode_progress(job_id, min_tokens=0)
            f.worker_for(job_id).bus.dead = True  # type: ignore[attr-defined]

        text, res = await f.run(chaos=kill_at_seed_watermark,
                                chaos_wait=False, temperature=0.9)
        assert res.success, res.error
        # the load-bearing check already ran inside run(): the delivered
        # stream equals the final text — no splice of divergent samples
        # (a sampled run may stop at EOS before num_predict, so the
        # token count itself is not asserted)
        assert text
        assert int(res.response.eval_count or 0) > 0
        assert f.job_count("orphaned") >= 1
        assert f.resume_count("stamped") >= 1  # seed-only stamp counts


async def test_graceful_drain_live_migrates_mid_decode():
    """Graceful drain mid-decode: the draining worker suspends the
    decode, migrates its KV to the peer, and the scheduler moves the
    assignment on ``job:drain`` — the stream continues seamlessly with
    zero lost and zero duplicated tokens, and the drained worker takes
    no new work while it winds down."""
    # a longer decode + the earliest possible trigger: drain() has a few
    # event-loop hops of latency, and a warm engine can burst through a
    # short tail before the suspend lands
    n_drain = 96
    text_ref, evals_ref = await reference_run(n=n_drain)
    async with Fleet(2) as f:
        drained: list[WorkerService] = []

        async def drain(job_id: str) -> None:
            await f.wait_decode_progress(job_id, min_tokens=2)
            victim = f.worker_for(job_id)
            drained.append(victim)
            report = await victim.drain(budget_ms=0)
            assert report["suspended"] == 1

        text, res = await f.run(n=n_drain, chaos=drain, chaos_wait=False)
        assert res.success, res.error
        assert text == text_ref
        assert int(res.response.eval_count or 0) == evals_ref
        assert f.resume_count("drain_handoff") == 1
        victim = drained[0]
        survivor = next(w for w in f.workers if w is not victim)
        assert res.workerId == survivor.worker_id
        # zero token loss: nothing was orphaned, nothing retried
        assert f.job_count("orphaned") == 0
        assert f.job_count("retried") == 0
        # the drained worker advertises "draining" and receives no new
        # work — the next request lands on the survivor
        for _ in range(40):
            w = f.registry.get_worker(victim.worker_id)
            if w is not None and w.status == "draining":
                break
            await asyncio.sleep(0.05)
        assert f.registry.get_worker(victim.worker_id).status == "draining"
        text2, res2 = await f.run(n=n_drain)
        assert res2.success and text2 == text_ref
        assert res2.workerId == survivor.worker_id


async def test_drain_migration_fault_falls_back_to_resume_requeue():
    """Satellite 3's mid-migration death, deterministically: the drain's
    KV send fails (injected ``kvx.send``), so the handoff degrades to a
    front-requeue WITH the resume snapshot — the stream still completes
    exactly-once on the peer."""
    n_drain = 96
    text_ref, evals_ref = await reference_run(n=n_drain)
    async with Fleet(2) as f:

        async def drain_with_send_fault(job_id: str) -> None:
            await f.wait_decode_progress(job_id, min_tokens=2)
            faults.configure("kvx.send=@1", seed=11)
            victim = f.worker_for(job_id)
            await victim.drain(budget_ms=0)

        text, res = await f.run(n=n_drain, chaos=drain_with_send_fault,
                                chaos_wait=False)
        assert res.success, res.error
        assert text == text_ref
        assert int(res.response.eval_count or 0) == evals_ref
        assert f.resume_count("drain_handoff") == 0
        assert f.resume_count("drain_requeued") == 1
        assert f.resume_count("stamped") >= 1
        from gridllm_tpu.faults import _INJECTED

        assert int(_INJECTED.value(site="kvx.send")) >= 1


@pytest.mark.slow
async def test_rolling_restart_over_resp_broker_zero_token_loss():
    """fault-smoke (satellite 5): a rolling restart over a REAL RESP
    broker. Worker w0 serves a stream, drains mid-decode (live-migrating
    the decode to w1) and stops; a replacement w2 comes up; then w1
    drains mid-stream too and the decode lands on w2. Every client
    stream is byte-identical to the undisturbed run — zero tokens lost
    or duplicated across two generations of workers."""
    from gridllm_tpu.bus import create_bus
    from gridllm_tpu.bus.broker import GridBusBroker

    # a longer decode than the in-process tests: broker latency delays
    # the client-side chaos trigger, and the drain must land while the
    # engine still holds the slot
    n_roll = 96
    text_ref, _ = await reference_run(n=n_roll)

    broker = GridBusBroker()
    await broker.start(port=0)
    url = f"resp://127.0.0.1:{broker.port}"
    bus = create_bus(url)
    await bus.connect()
    # generous liveness: drains are EXPLICIT here, and first-compile GIL
    # pressure over a real broker starves heartbeats long enough to trip
    # sub-second probes into false positives (worker removed -> no peer)
    cfg = ft_config(worker_heartbeat_timeout_ms=60_000,
                    worker_cleanup_interval_ms=1_000,
                    connection_monitor_interval_ms=1_000,
                    quick_disconnect_window_ms=30_000,
                    orphan_assign_threshold_ms=30_000)
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    workers: dict[str, WorkerService] = {}
    worker_buses = []

    async def spawn(wid: str) -> WorkerService:
        wbus = create_bus(url)
        await wbus.connect()
        worker_buses.append(wbus)
        svc = WorkerService(
            wbus, {MODEL: make_engine()},
            WorkerConfig(worker_id=wid, heartbeat_interval_ms=150),
            stream_flush_ms=5)
        svc._snap_every = 2
        await svc.start()
        workers[wid] = svc
        return svc

    async def run_stream(drain_wid_holder: list) -> tuple[str, str]:
        chunks: list[str] = []

        async def on_chunk(c) -> None:
            chunks.append(c.response)

        req = InferenceRequest(
            id=f"roll-{uuid.uuid4().hex[:8]}", model=MODEL, prompt=PROMPT,
            stream=True,
            options={"temperature": 0, "num_predict": n_roll},
            metadata={"requestType": "inference"})
        task = asyncio.create_task(scheduler.submit_streaming_job(
            req, on_chunk, timeout_ms=150_000))
        # deterministic mid-decode point: the snapshot watermark, not
        # client-observed chars (those lag the engine under load)
        for _ in range(12000):
            snap = scheduler._resume_snap.get(req.id)
            if snap is not None and len(snap["tokens"]) >= CHAOS_TOKENS:
                break
            await asyncio.sleep(0.01)
        victim_id = scheduler.active_jobs[req.id].workerId
        drain_wid_holder.append(victim_id)
        report = await workers[victim_id].drain(budget_ms=0)
        assert report["suspended"] == 1, report
        res = await task
        assert res.success, res.error
        return "".join(chunks), res.workerId

    try:
        await spawn("roll-w0")
        await spawn("roll-w1")
        for _ in range(600):
            if len(registry.get_online_workers()) == 2:
                break
            await asyncio.sleep(0.1)
        assert len(registry.get_online_workers()) == 2

        # round 1: the serving worker drains mid-stream, peer finishes
        drained1: list[str] = []
        text1, served1 = await run_stream(drained1)
        assert text1 == text_ref
        assert served1 != drained1[0]
        # the drained worker restarts as a fresh instance
        await workers[drained1[0]].stop(announce=False)
        await spawn("roll-w2")
        for _ in range(600):
            live = {w.workerId for w in registry.get_online_workers()}
            if "roll-w2" in live:
                break
            await asyncio.sleep(0.1)

        # round 2: the survivor of round 1 drains mid-stream too
        drained2: list[str] = []
        text2, served2 = await run_stream(drained2)
        assert text2 == text_ref
        assert served2 != drained2[0]
        assert int(scheduler._resume_total.value(
            event="drain_handoff")) == 2
        # zero token loss across the whole rolling restart
        assert int(scheduler._jobs_total.value(event="orphaned")) == 0
    finally:
        for svc in workers.values():
            await svc.stop(announce=False)
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()
        for wbus in worker_buses:
            await wbus.disconnect()
        await broker.stop()


async def test_engine_step_fault_recovers_exactly_once():
    """An injected engine-step crash mid-decode takes the runner's
    abort-and-rebuild path; the failed attempt retries WITH its resume
    watermark and the client stream is still byte-identical."""
    text_ref, evals_ref = await reference_run()
    async with Fleet(1) as f:

        async def crash_next_step(_job_id: str) -> None:
            faults.configure("engine.step=@1", seed=5)

        text, res = await f.run(chaos=crash_next_step)
        assert res.success, res.error
        assert text == text_ref
        assert int(res.response.eval_count or 0) == evals_ref
        assert f.job_count("retried") >= 1
