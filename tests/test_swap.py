"""Elastic serving (ISSUE 20): host-RAM weight snapshot tier, swap
fault sites, admin load/unload hardening, the demand-driven placement
controller, scale-to-zero byte-determinism, capacity alias dedup, and
canary zero-replica skip."""

import asyncio
import json
import time
import types
import uuid

import numpy as np
import pytest

from gridllm_tpu import faults
from gridllm_tpu.bus.base import CH_WORKER_ADMIN, admin_result_channel
from gridllm_tpu.bus.memory import InMemoryBus
from gridllm_tpu.engine import EngineConfig, InferenceEngine, loader
from gridllm_tpu.engine.engine import GenerationRequest
from gridllm_tpu.engine.loader import WeightSnapshotTier
from gridllm_tpu.obs.capacity import (aggregate_worker_capacity,
                                      dedup_capacity_totals)
from gridllm_tpu.obs.metrics import MetricsRegistry
from gridllm_tpu.obs.probe import CanaryProber
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.scheduler.placement import (ModelPlacementController,
                                             parse_floors)
from gridllm_tpu.utils.config import WorkerConfig
from gridllm_tpu.utils.types import (InferenceRequest, ModelInfo,
                                     NodeCapabilities, WorkerInfo)
from gridllm_tpu.worker.service import WorkerService
from tests.helpers import fast_config


def _tiny_engine(name: str) -> InferenceEngine:
    return InferenceEngine(EngineConfig(
        model=name, max_slots=1, page_size=8, num_pages=32,
        max_pages_per_slot=4, prefill_buckets=(16, 32),
    ))


def _greedy(eng: InferenceEngine, seed: int = 7) -> str:
    return eng.generate(GenerationRequest(
        id=f"swaptest-{uuid.uuid4().hex[:6]}",
        prompt="the quick brown fox",
        options={"temperature": 0, "seed": seed, "num_predict": 4},
    )).text


@pytest.fixture
def snapshot_tier(monkeypatch):
    """Enable the weight snapshot tier for one test; always reset the
    singleton so no other test inherits an enabled tier."""
    monkeypatch.setenv("GRIDLLM_WEIGHT_SNAPSHOT_BYTES", str(1 << 30))
    loader.reset_weight_snapshot_tier()
    yield loader.weight_snapshot_tier()
    loader.reset_weight_snapshot_tier()


# ---------------------------------------------------------- snapshot tier


def test_tier_lru_eviction_and_stats():
    tier = WeightSnapshotTier(capacity_bytes=10_000)
    blob = {"w": np.ones((1000,), np.float32)}  # 4000 bytes
    assert tier.park("k1", blob) and tier.park("k2", blob)
    assert tier.restore("k1") is not None  # k1 → MRU; k2 is now LRU
    assert tier.park("k3", blob)           # over budget → evicts k2
    assert tier.restore("k2") is None      # miss
    assert tier.restore("k1") is not None  # survivors: restore keeps entries
    assert tier.restore("k3") is not None
    s = tier.stats()
    assert s["entries"] == 2 and s["evictions"] == 1 and s["misses"] == 1
    assert s["parks"] == 3 and s["hits"] == 3 and s["bytes"] == 8000

    # a snapshot alone above capacity is refused, not half-admitted
    small = WeightSnapshotTier(capacity_bytes=100)
    assert not small.park("big", blob)
    assert small.stats()["entries"] == 0

    # disabled tier (capacity 0) parks nothing
    off = WeightSnapshotTier(capacity_bytes=0)
    assert not off.enabled and not off.park("k", blob)


def test_park_restore_byte_identical(snapshot_tier):
    eng1 = _tiny_engine("tiny-llama")
    assert eng1.load_source == "init"
    text1 = _greedy(eng1)
    assert eng1.park_weights()
    assert eng1.params is None  # device refs dropped on park

    eng2 = _tiny_engine("tiny-llama")
    assert eng2.load_source == "snapshot"
    assert _greedy(eng2) == text1  # byte-identical across park/restore
    assert snapshot_tier.stats()["hits"] == 1


def test_snapshot_restore_fault_degrades_to_disk(snapshot_tier):
    eng1 = _tiny_engine("tiny-llama")
    text1 = _greedy(eng1)
    assert eng1.park_weights()
    faults.configure("swap.snapshot_restore=@1")
    try:
        eng2 = _tiny_engine("tiny-llama")
        # the restore fault degrades to the init/disk path — the load
        # completes and (init is seeded) still serves identical bytes
        assert eng2.load_source == "init"
        assert _greedy(eng2) == text1
    finally:
        faults.reset()
    # the parked snapshot is untouched: the NEXT load hits it
    eng3 = _tiny_engine("tiny-llama")
    assert eng3.load_source == "snapshot"


# ------------------------------------------------- worker admin hardening


async def _admin_op(bus, op: str, model: str, worker_id: str | None = None,
                    timeout: float = 60.0, **extra) -> dict:
    rid = uuid.uuid4().hex[:12]
    got: dict = {}
    done = asyncio.Event()

    async def on_result(_ch, raw):
        msg = json.loads(raw)
        if "ok" in msg:
            got.update(msg)
            done.set()

    sub = await bus.subscribe(admin_result_channel(rid), on_result)
    payload = {"op": op, "id": rid, "model": model, **extra}
    if worker_id is not None:
        payload["workerId"] = worker_id
    try:
        await bus.publish(CH_WORKER_ADMIN, json.dumps(payload))
        await asyncio.wait_for(done.wait(), timeout)
    finally:
        await sub.unsubscribe()
    return got


async def _worker_stack(factory=None):
    bus = InMemoryBus()
    await bus.connect()
    worker = WorkerService(
        bus, {"tiny-llama": _tiny_engine("tiny-llama")},
        WorkerConfig(worker_id="swap-w1", heartbeat_interval_ms=200,
                     resource_monitor_interval_ms=500),
        stream_flush_ms=5, engine_factory=factory,
    )
    await worker.start()
    await asyncio.sleep(0.05)
    return bus, worker


async def test_admin_load_race_single_engine():
    """Two concurrent load ops for the same model build exactly ONE
    engine (single-flight under the admin lock); both callers get ok."""
    calls: list[str] = []

    def factory(name: str) -> InferenceEngine:
        calls.append(name)
        time.sleep(0.2)  # widen the race window across the to_thread hop
        return _tiny_engine(name)

    bus, worker = await _worker_stack(factory)
    try:
        r1, r2 = await asyncio.gather(
            _admin_op(bus, "load_model", "tiny-qwen2"),
            _admin_op(bus, "load_model", "tiny-qwen2"))
        assert r1["ok"] and r2["ok"], (r1, r2)
        assert calls == ["tiny-qwen2"]  # second op saw "already loaded"
        assert worker.engines["tiny-qwen2"].running
    finally:
        await worker.stop()
        await bus.disconnect()


async def test_targeted_admin_op_only_named_worker_answers():
    bus, worker = await _worker_stack(_tiny_engine)
    try:
        # an op addressed to a DIFFERENT worker gets silence (no ack, no
        # result) — the named worker is the only one allowed to answer
        with pytest.raises(asyncio.TimeoutError):
            await _admin_op(bus, "load_model", "tiny-qwen2",
                            worker_id="someone-else", timeout=0.6)
        assert "tiny-qwen2" not in worker.engines
        r = await _admin_op(bus, "load_model", "tiny-qwen2",
                            worker_id="swap-w1")
        assert r["ok"] and "tiny-qwen2" in worker.engines
    finally:
        await worker.stop()
        await bus.disconnect()


async def test_swap_load_fault_answers_not_ok_no_orphan():
    calls: list[str] = []

    def factory(name: str) -> InferenceEngine:
        calls.append(name)
        return _tiny_engine(name)

    bus, worker = await _worker_stack(factory)
    faults.configure("swap.load=@1")
    try:
        r = await _admin_op(bus, "load_model", "tiny-qwen2")
        assert not r["ok"] and "injected fault" in r["detail"]
        assert "tiny-qwen2" not in worker.engines
        assert calls == []  # faulted before construction: nothing leaked
        faults.reset()
        r = await _admin_op(bus, "load_model", "tiny-qwen2")
        assert r["ok"] and worker.engines["tiny-qwen2"].running
    finally:
        faults.reset()
        await worker.stop()
        await bus.disconnect()


async def test_swap_unload_fault_model_stays_servable():
    bus, worker = await _worker_stack()
    faults.configure("swap.unload=@1")
    try:
        r = await _admin_op(bus, "unload_model", "tiny-llama")
        assert not r["ok"] and "injected fault" in r["detail"]
        eng = worker.engines["tiny-llama"]  # still resident
        assert eng.running  # and still servable
        faults.reset()
        r = await _admin_op(bus, "unload_model", "tiny-llama")
        assert r["ok"] and not worker.engines
    finally:
        faults.reset()
        await worker.stop()
        await bus.disconnect()


# ------------------------------------------------ placement controller


class _FakeSched:
    def __init__(self):
        self.models: dict = {}
        self.capacity = types.SimpleNamespace(
            snapshot=lambda: {"models": self.models, "fleet": {}})
        self.dispatches = 0

    def request_dispatch(self):
        self.dispatches += 1


class _W:
    def __init__(self, wid, models, slots=4, jobs=0, health="online"):
        self.workerId = wid
        self._models = list(models)
        self.decodeSlotsFree = slots
        self.currentJobs = jobs
        self.healthState = health

    def model_names(self):
        return list(self._models)


class _FakeReg:
    def __init__(self, workers):
        self.workers = workers

    def get_workers_with_model(self, model):
        return [w for w in self.workers if model in w.model_names()]

    def get_online_workers(self):
        return list(self.workers)


async def _ctrl_stack(monkeypatch, workers, *, cooldown_ms=60_000,
                      idle_ttl_ms=100, floors=""):
    monkeypatch.setenv("GRIDLLM_PLACEMENT_INTERVAL_MS", "50")
    monkeypatch.setenv("GRIDLLM_MODEL_IDLE_TTL_MS", str(idle_ttl_ms))
    monkeypatch.setenv("GRIDLLM_SWAP_COOLDOWN_MS", str(cooldown_ms))
    monkeypatch.setenv("GRIDLLM_MODEL_FLOORS", floors)
    bus = InMemoryBus()
    await bus.connect()
    ops: list[dict] = []

    async def responder(_ch, raw):
        msg = json.loads(raw)
        ops.append(msg)
        await bus.publish(admin_result_channel(msg["id"]), json.dumps({
            "workerId": msg["workerId"], "op": msg["op"], "ok": True,
            "detail": "done"}))

    await bus.subscribe(CH_WORKER_ADMIN, responder)
    sched = _FakeSched()
    ctrl = ModelPlacementController(
        sched, _FakeReg(workers), bus, MetricsRegistry())
    assert ctrl.enabled
    return bus, sched, ctrl, ops


async def test_placement_swaps_in_unserved_model(monkeypatch):
    w1 = _W("w1", ["m1"])
    bus, sched, ctrl, ops = await _ctrl_stack(monkeypatch, [w1])
    try:
        sched.models = {"m2": {"queueDepth": 2}}
        await ctrl.tick()
        assert [(o["op"], o["model"], o["workerId"]) for o in ops] == \
            [("load_model", "m2", "w1")]
        assert not ops[0]["if_idle"]
        assert sched.dispatches == 1  # held jobs drained after the load
        assert ctrl._swaps.value(op="load", outcome="ok") == 1
    finally:
        await bus.disconnect()


async def test_placement_idle_unload_respects_ttl_and_floor(monkeypatch):
    w1 = _W("w1", ["m1"])
    bus, sched, ctrl, ops = await _ctrl_stack(monkeypatch, [w1])
    try:
        sched.models = {"m1": {"queueDepth": 0, "arrivalRate": 0.0,
                               "utilization": 0.0}}
        await ctrl.tick()
        assert ops == []  # first sight stamps activity: full TTL first
        ctrl._last_active["m1"] = time.monotonic() - 10.0
        await ctrl.tick()
        assert [(o["op"], o["model"]) for o in ops] == \
            [("unload_model", "m1")]
        assert ops[0]["if_idle"]  # unloads are ALWAYS conditional

        # a floor pins the model resident even when idle past the TTL
        ops.clear()
        ctrl.floors = {"m1": 1}
        ctrl._last_action.clear()
        ctrl._last_active["m1"] = time.monotonic() - 10.0
        await ctrl.tick()
        assert ops == []
    finally:
        await bus.disconnect()


async def test_placement_restores_floor_and_cooldown_gates(monkeypatch):
    w1, w2 = _W("w1", ["m1"]), _W("w2", [])
    bus, sched, ctrl, ops = await _ctrl_stack(
        monkeypatch, [w1, w2], floors="m2=1")
    try:
        # m2 under its floor with zero replicas → urgent load (cooldown
        # cannot hold it); target is the emptier worker w2
        await ctrl.tick()
        assert [(o["op"], o["model"], o["workerId"]) for o in ops] == \
            [("load_model", "m2", "w2")]
        w2._models.append("m2")  # the worker's heartbeat catches up

        # scale-up with replicas present is NOT urgent: the 60s cooldown
        # holds the second action
        ops.clear()
        sched.models = {"m1": {"queueDepth": 3, "scaleHint": 1}}
        await ctrl.tick()
        assert [(o["op"], o["model"]) for o in ops] == \
            [("load_model", "m1")]
        ops.clear()
        await ctrl.tick()
        assert ops == []  # held by hysteresis
    finally:
        await bus.disconnect()


def test_parse_floors():
    assert parse_floors("a=2, b=1") == {"a": 2, "b": 1}
    assert parse_floors("a=2,b=oops,c=-1") == {"a": 2, "c": 0}
    assert parse_floors("") == {}


# ------------------------------------- scale-to-zero differential (e2e)


async def _full_stack(factory=None):
    bus = InMemoryBus()
    await bus.connect()
    cfg = fast_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    worker = WorkerService(
        bus, {"tiny-llama": _tiny_engine("tiny-llama")},
        WorkerConfig(worker_id="swap-e2e", heartbeat_interval_ms=150,
                     resource_monitor_interval_ms=500),
        stream_flush_ms=5, engine_factory=factory,
    )
    await worker.start()
    await asyncio.sleep(0.1)
    return bus, registry, scheduler, worker


async def _serve_once(scheduler, model: str) -> str:
    res = await scheduler.submit_and_wait(InferenceRequest(
        id=f"swapdiff-{uuid.uuid4().hex[:8]}", model=model,
        prompt="the quick brown fox",
        options={"temperature": 0, "seed": 7, "num_predict": 4},
        metadata={"requestType": "inference"},
    ), timeout_ms=90_000)
    assert res.success, res.error
    return res.response.response


async def test_scale_to_zero_stream_byte_identical(monkeypatch, snapshot_tier):
    """The acceptance differential: greedy fixed-seed output is
    byte-identical with elasticity OFF, with elasticity ON, and ACROSS a
    full unload → queue → automatic swap-in → serve cycle."""
    # ---- static arm: no placement controller
    monkeypatch.setenv("GRIDLLM_PLACEMENT_INTERVAL_MS", "0")
    bus, registry, scheduler, worker = await _full_stack()
    try:
        assert not scheduler.placement.enabled
        text_static = await _serve_once(scheduler, "tiny-llama")
    finally:
        await worker.stop()
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()

    # ---- elastic arm: fast ticks, short TTL, short demand half-life
    # (the default 60s EWMA would hold the model "busy" for minutes)
    monkeypatch.setenv("GRIDLLM_PLACEMENT_INTERVAL_MS", "50")
    monkeypatch.setenv("GRIDLLM_MODEL_IDLE_TTL_MS", "300")
    monkeypatch.setenv("GRIDLLM_SWAP_COOLDOWN_MS", "50")
    monkeypatch.setenv("GRIDLLM_CAPACITY_EWMA_HALFLIFE_S", "0.05")
    bus, registry, scheduler, worker = await _full_stack(_tiny_engine)
    try:
        assert scheduler.placement.enabled
        assert await _serve_once(scheduler, "tiny-llama") == text_static

        # idle past the TTL → the controller unloads the model; the
        # worker parks its weights and drops all capacity for it
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and worker.engines:
            await asyncio.sleep(0.1)
        assert not worker.engines, "model never scaled to zero"
        assert not worker._model_capacity()  # slot/KV gauges source gone
        assert snapshot_tier.stats()["entries"] == 1  # weights parked

        # zero-replica request: QUEUED (not rejected), swap-in triggered
        # by the dispatch pass, served from the weight snapshot — and
        # still byte-identical to the static arm
        assert await _serve_once(scheduler, "tiny-llama") == text_static
        assert worker.engines["tiny-llama"].load_source == "snapshot"
        p = scheduler.placement
        assert p._swaps.value(op="unload", outcome="ok") >= 1
        assert p._swaps.value(op="load", outcome="ok") >= 1
    finally:
        await worker.stop()
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()


# ------------------------------------------- canary zero-replica skip


class _StubReg:
    def __init__(self, workers):
        self._workers = workers

    def get_all_workers(self):
        return self._workers


def _winfo(resident: list[str], advertised: list[ModelInfo]) -> WorkerInfo:
    return WorkerInfo(
        workerId="w1",
        capabilities=NodeCapabilities(workerId="w1",
                                      availableModels=advertised),
        modelCapacity={m: {"slotsFree": 1, "slotsTotal": 1,
                           "kvPagesFree": 8, "engine": 1}
                       for m in resident},
    )


def test_canary_skips_zero_replica_models():
    w = _winfo(resident=["m1"], advertised=[
        ModelInfo(name="m1"),
        ModelInfo(name="m2"),  # mid-unload: no capacity block → skipped
        ModelInfo(name="emb", details={"family": "bert_embed"}),
    ])
    prober = CanaryProber(scheduler=None, registry=_StubReg([w]),
                          health=None, metrics=MetricsRegistry())
    targets = {m for _, m in prober._targets()}
    # embedding-only models never report slot capacity and stay probed
    assert targets == {"m1", "emb"}

    # a worker with NO capacity map at all (older heartbeat shape) keeps
    # the old behavior: everything advertised is probed
    w2 = _winfo(resident=[], advertised=[ModelInfo(name="m1")])
    prober2 = CanaryProber(scheduler=None, registry=_StubReg([w2]),
                           health=None, metrics=MetricsRegistry())
    assert {m for _, m in prober2._targets()} == {"m1"}


# ------------------------------------------------ capacity alias dedup


def test_dedup_capacity_totals_counts_alias_pool_once():
    shared = {"slotsFree": 2, "slotsTotal": 4, "kvPagesFree": 10,
              "engine": 77}
    w = types.SimpleNamespace(modelCapacity={"a": dict(shared),
                                             "b": dict(shared)})
    # per-name attribution stays duplicated on purpose (either name can
    # use the shared pool) ...
    agg = aggregate_worker_capacity([w])
    assert agg["a"]["slotsTotal"] == 4 and agg["b"]["slotsTotal"] == 4
    # ... but the fleet total counts the engine once
    tot = dedup_capacity_totals([w])
    assert tot == {"slotsFree": 2, "slotsTotal": 4, "kvPagesFree": 10,
                   "engines": 1}

    # blocks without an engine token (older workers) count per name
    legacy = types.SimpleNamespace(modelCapacity={
        "x": {"slotsFree": 1, "slotsTotal": 2, "kvPagesFree": 4},
        "y": {"slotsFree": 1, "slotsTotal": 2, "kvPagesFree": 4}})
    tot = dedup_capacity_totals([w, legacy])
    assert tot["slotsTotal"] == 4 + 4 and tot["engines"] == 3
