"""Model management e2e: /api/pull (load-on-demand), /api/delete,
/api/copy through gateway → bus admin broadcast → WorkerService.

The reference shipped dead client-side pullModel/deleteModel stubs with
no routes (client/src/services/OllamaService.ts:286-331); these are the
rebuild's live cluster equivalents (VERDICT r03 missing #6).
"""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from gridllm_tpu.bus.memory import InMemoryBus
from gridllm_tpu.engine import EngineConfig, InferenceEngine
from gridllm_tpu.gateway.app import create_app
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.config import Config, WorkerConfig
from gridllm_tpu.worker.service import WorkerService
from tests.helpers import fast_config


def _tiny_factory(name: str) -> InferenceEngine:
    return InferenceEngine(EngineConfig(
        model=name, max_slots=1, page_size=8, num_pages=32,
        max_pages_per_slot=4, prefill_buckets=(16, 32),
    ))


async def _stack(engine_factory=None):
    bus = InMemoryBus()
    await bus.connect()
    sched_cfg = fast_config()
    registry = WorkerRegistry(bus, sched_cfg)
    scheduler = JobScheduler(bus, registry, sched_cfg)
    await registry.initialize()
    await scheduler.initialize()
    config = Config()
    config.scheduler = sched_cfg
    app = create_app(bus, registry, scheduler, config)
    worker = WorkerService(
        bus, {"tiny-llama": _tiny_factory("tiny-llama")},
        WorkerConfig(heartbeat_interval_ms=150,
                     resource_monitor_interval_ms=500),
        stream_flush_ms=5,
        engine_factory=engine_factory,
    )
    await worker.start()
    await asyncio.sleep(0.05)
    client = TestClient(TestServer(app))
    await client.start_server()
    return bus, registry, scheduler, worker, client


async def _teardown(registry, scheduler, worker, client, bus):
    await client.close()
    await worker.stop()
    await scheduler.shutdown()
    await registry.shutdown()
    await bus.disconnect()


async def test_pull_loads_model_and_serves_it():
    bus, registry, scheduler, worker, client = await _stack(_tiny_factory)
    try:
        # a model no worker can build still 404s (fast: workers ACK the
        # admin broadcast, attempt the load, and reply not-ok)
        r = await client.post("/ollama/api/generate", json={
            "model": "no-such-model", "prompt": "x", "stream": False})
        assert r.status == 404

        r = await client.post("/ollama/api/pull", json={
            "model": "tiny-qwen2", "stream": True})
        assert r.status == 200
        frames = [json.loads(x) for x in (await r.text()).strip().splitlines()]
        assert frames[0]["status"] == "pulling manifest"
        assert frames[-1]["status"] == "success"
        assert "tiny-qwen2" in worker.engines

        await asyncio.sleep(0.1)  # registration propagation
        r = await client.post("/ollama/api/generate", json={
            "model": "tiny-qwen2", "prompt": "hello", "stream": False,
            "options": {"temperature": 0, "num_predict": 3}})
        body = await r.json()
        assert r.status == 200 and body["done"], body
    finally:
        await _teardown(registry, scheduler, worker, client, bus)


async def test_pull_without_factory_fails_cleanly():
    bus, registry, scheduler, worker, client = await _stack(None)
    try:
        r = await client.post("/ollama/api/pull", json={
            "model": "tiny-qwen2", "stream": False})
        assert r.status == 500
        assert "disabled" in (await r.text())
    finally:
        await _teardown(registry, scheduler, worker, client, bus)


async def test_copy_aliases_and_delete_unloads():
    bus, registry, scheduler, worker, client = await _stack(_tiny_factory)
    try:
        r = await client.post("/ollama/api/copy", json={
            "source": "tiny-llama", "destination": "my-alias"})
        assert r.status == 200
        assert worker.engines["my-alias"] is worker.engines["tiny-llama"]

        await asyncio.sleep(0.1)
        r = await client.post("/ollama/api/generate", json={
            "model": "my-alias", "prompt": "hi", "stream": False,
            "options": {"temperature": 0, "num_predict": 2}})
        assert r.status == 200, await r.text()

        # delete the alias: original must keep serving (shared engine not
        # stopped while another name references it)
        r = await client.delete("/ollama/api/delete",
                                json={"model": "my-alias"})
        assert r.status == 200
        assert "my-alias" not in worker.engines
        assert worker.engines["tiny-llama"].running

        # delete the last name → engine stops
        r = await client.delete("/ollama/api/delete",
                                json={"model": "tiny-llama"})
        assert r.status == 200
        assert not worker.engines

        r = await client.delete("/ollama/api/delete",
                                json={"model": "never-existed"})
        assert r.status == 404
    finally:
        await _teardown(registry, scheduler, worker, client, bus)


async def test_keep_alive_zero_unloads_and_next_request_reloads():
    """Full Ollama residency semantics: empty prompt + keep_alive=0
    REALLY unloads the weights; the next generate for the model
    auto-loads it back (load-on-demand), no explicit pull needed."""
    bus, registry, scheduler, worker, client = await _stack(_tiny_factory)
    try:
        r = await client.post("/ollama/api/generate", json={
            "model": "tiny-llama", "prompt": "", "keep_alive": 0,
            "stream": False})
        body = await r.json()
        assert r.status == 200 and body["done_reason"] == "unload", body
        assert "tiny-llama" not in worker.engines  # weights actually gone

        await asyncio.sleep(0.1)
        r = await client.post("/ollama/api/generate", json={
            "model": "tiny-llama", "prompt": "back again", "stream": False,
            "options": {"temperature": 0, "num_predict": 3}})
        body = await r.json()
        assert r.status == 200 and body["done"], body
        assert "tiny-llama" in worker.engines  # auto-reloaded
    finally:
        await _teardown(registry, scheduler, worker, client, bus)


async def test_openai_surface_loads_on_demand():
    """The OpenAI surface shares the same residency semantics (ONE
    ModelAdmin per app): a cold model is loaded on request."""
    bus, registry, scheduler, worker, client = await _stack(_tiny_factory)
    try:
        r = await client.post("/v1/chat/completions", json={
            "model": "tiny-qwen2", "max_tokens": 4, "temperature": 0,
            "messages": [{"role": "user", "content": "hi"}]})
        body = await r.json()
        assert r.status == 200, body
        assert body["choices"][0]["message"]["role"] == "assistant", body
        assert "tiny-qwen2" in worker.engines  # loaded on demand
    finally:
        await _teardown(registry, scheduler, worker, client, bus)


async def test_enforce_keep_alive_sweeps_idle_models():
    """Opt-in Ollama idle residency: when a model's keep_alive window
    passes without requests, the sweeper REALLY unloads it (and the next
    request can auto-load it back)."""
    import time as _time

    from gridllm_tpu.gateway.admin import ModelAdmin

    bus = InMemoryBus()
    await bus.connect()
    sched_cfg = fast_config()
    registry = WorkerRegistry(bus, sched_cfg)
    await registry.initialize()
    worker = WorkerService(
        bus, {"tiny-llama": _tiny_factory("tiny-llama")},
        WorkerConfig(heartbeat_interval_ms=150,
                     resource_monitor_interval_ms=500),
        stream_flush_ms=5, engine_factory=_tiny_factory,
    )
    await worker.start()
    await asyncio.sleep(0.05)

    admin = ModelAdmin(registry, 30_000)
    admin.model_expiry["tiny-llama"] = _time.time() + 0.2  # expires soon
    admin.start_keep_alive_sweeper(interval_s=0.1)
    try:
        for _ in range(100):
            if "tiny-llama" not in worker.engines:
                break
            await asyncio.sleep(0.1)
        assert "tiny-llama" not in worker.engines  # really unloaded
        assert "tiny-llama" not in admin.model_expiry

        # next request path can bring it back (load-on-demand)
        assert await admin.ensure_servable("tiny-llama")
        assert "tiny-llama" in worker.engines
    finally:
        await admin.stop_keep_alive_sweeper()
        await worker.stop()
        await registry.shutdown()
        await bus.disconnect()
