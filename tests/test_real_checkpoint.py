"""Real-checkpoint end-to-end (VERDICT.md #3).

Builds a GENUINE on-disk HF checkpoint locally (zero egress): a trained
BPE tokenizer (tokenizer.json via the `tokenizers` library) and a
`LlamaForCausalLM` saved with safe_serialization — the same file layout a
downloaded HF Llama has. Then serves it through the FULL stack (engine
loader + HF tokenizer + gateway /ollama/api/generate) and compares greedy
output token-for-token against `transformers` `model.generate`.

This replaces what the reference delegated to Ollama
(client/src/services/OllamaService.ts:97-184) with a checked contract:
same weights on disk → same tokens out.
"""

import asyncio
import json

import numpy as np
import pytest

CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump! "
    "sphinx of black quartz judge my vow. "
) * 8


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    """Tiny but REAL HF checkpoint dir: config.json + model.safetensors +
    tokenizer.json/tokenizer_config.json."""
    import torch
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers
    from transformers import LlamaConfig, LlamaForCausalLM, PreTrainedTokenizerFast

    path = tmp_path_factory.mktemp("hf-tiny-llama")

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.train_from_iterator(
        [CORPUS],
        trainers.BpeTrainer(vocab_size=384, special_tokens=["<s>", "</s>"]),
    )
    hf_tok = PreTrainedTokenizerFast(
        tokenizer_object=tok, bos_token="<s>", eos_token="</s>"
    )
    hf_tok.save_pretrained(path)

    torch.manual_seed(0)
    config = LlamaConfig(
        vocab_size=len(hf_tok),
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        rope_theta=10_000.0,
        max_position_embeddings=256,
        tie_word_embeddings=False,
        torch_dtype="float32",
    )
    model = LlamaForCausalLM(config)
    model.save_pretrained(path, safe_serialization=True)
    return path, model, hf_tok


def _torch_greedy(model, hf_tok, prompt: str, n: int) -> list[int]:
    import torch

    ids = [hf_tok.bos_token_id] + hf_tok.encode(prompt, add_special_tokens=False)
    with torch.no_grad():
        out = model.generate(
            input_ids=torch.tensor([ids]),
            max_new_tokens=n, do_sample=False,
            eos_token_id=None,  # run the full n tokens
            pad_token_id=hf_tok.eos_token_id,
        )
    return out[0][len(ids):].tolist()


def test_engine_matches_transformers_generate(hf_checkpoint):
    """Loader + HF tokenizer + engine greedy == transformers greedy."""
    from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine

    path, model, hf_tok = hf_checkpoint
    eng = InferenceEngine(EngineConfig(
        model="local-tiny-llama",          # NOT in the registry → config.json
        checkpoint_path=str(path),
        tokenizer=str(path),
        dtype="float32",
        max_slots=2, page_size=8, num_pages=64, max_pages_per_slot=16,
        prefill_buckets=(16, 32),
    ))
    prompt = "the quick brown fox"
    res = eng.generate(GenerationRequest(
        id="g", prompt=prompt,
        options={"temperature": 0.0, "num_predict": 12},
    ))
    want = _torch_greedy(model, hf_tok, prompt, 12)
    assert res.token_ids == want[: len(res.token_ids)]
    assert len(res.token_ids) == 12  # random-init should not emit EOS here
    # detokenized text round-trips through the same tokenizer files
    assert res.text == hf_tok.decode(want, skip_special_tokens=True)


async def _serve_and_generate(path, prompt: str, n: int) -> dict:
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.bus.memory import InMemoryBus
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.gateway.app import create_app
    from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
    from gridllm_tpu.utils.config import Config, WorkerConfig
    from gridllm_tpu.worker.service import WorkerService

    engine = InferenceEngine(EngineConfig(
        model="local-tiny-llama", checkpoint_path=str(path),
        tokenizer=str(path), dtype="float32",
        max_slots=2, page_size=8, num_pages=64, max_pages_per_slot=16,
        prefill_buckets=(16, 32),
    ))
    bus = InMemoryBus()
    await bus.connect()
    cfg = Config()
    registry = WorkerRegistry(bus, cfg.scheduler)
    scheduler = JobScheduler(bus, registry, cfg.scheduler)
    await registry.initialize()
    await scheduler.initialize()
    app = create_app(bus, registry, scheduler, cfg)
    worker = WorkerService(bus, {"local-tiny-llama": engine}, WorkerConfig())
    await worker.start()
    await asyncio.sleep(0.1)
    client = TestClient(TestServer(app))
    await client.start_server()
    resp = await client.post("/ollama/api/generate", json={
        "model": "local-tiny-llama", "prompt": prompt, "stream": False,
        "options": {"temperature": 0.0, "num_predict": n},
    })
    assert resp.status == 200, await resp.text()
    body = await resp.json()
    await client.close()
    await worker.stop()
    await scheduler.shutdown()
    await registry.shutdown()
    await bus.disconnect()
    return body


def test_api_generate_serves_real_checkpoint(hf_checkpoint):
    """BASELINE configs #1-#2 shape: /ollama/api/generate on real weights,
    response text equal to the transformers continuation."""
    path, model, hf_tok = hf_checkpoint
    prompt = "pack my box"
    body = asyncio.run(_serve_and_generate(path, prompt, 10))
    want = _torch_greedy(model, hf_tok, prompt, 10)
    assert body["done"] and body["done_reason"] == "length"
    assert body["response"] == hf_tok.decode(want, skip_special_tokens=True)
    assert body["eval_count"] == 10
    assert body["prompt_eval_count"] == len(
        hf_tok.encode(prompt, add_special_tokens=False)) + 1
