"""Active fleet health tests (ISSUE 19): canary probes, per-worker
EWMA+z-score regression baselines, and the automatic-quarantine state
machine.

Covers the baseline math (decay, judged-before-fold z-scores), the full
``online → degraded → quarantined → probation`` round trip with its
metrics and registry replication, the canary golden-hash seal/drift law
end-to-end over the bus (pinned placement, drain request, forensics
incident naming the worker), the canary tenant's exclusion from both
usage-ledger halves and SLO attainment, the two new fault sites
(``probe.issue``, ``health.baseline``), and THE differentials: a worker
that silently slows down is detected by its canary-latency baseline and
quarantined with zero client-visible loss, and (slow, real engines) a
worker with silently perturbed sampling — same config hash, same
latency, wrong bytes — drifts against the sealed golden and is
quarantined after ONE canary while traffic keeps matching the healthy
reference byte-for-byte."""

import asyncio
import json
import os
import subprocess
import sys
import uuid
from pathlib import Path

import pytest

from gridllm_tpu import faults
from gridllm_tpu.bus import InMemoryBus
from gridllm_tpu.obs import MetricsRegistry
from gridllm_tpu.obs.flightrec import default_flight_recorder
from gridllm_tpu.obs.forensics import IncidentCollector
from gridllm_tpu.obs.health import (
    SIG_ITL,
    STATE_CODES,
    HealthMonitor,
    _Baseline,
)
from gridllm_tpu.obs.timeline import TimelinePublisher, TimelineStore, set_emitter
from gridllm_tpu.obs.usage import (
    CANARY_TENANT,
    UsageAccountant,
    account_engine_usage,
    build_usage,
    engine_usage_totals,
)
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.types import InferenceRequest, Priority

from .helpers import FakeWorker, fast_config

DRIFT_CHILD = Path(__file__).with_name("health_drift_child.py")


@pytest.fixture(autouse=True)
def _clean_slate():
    faults.reset()
    yield
    faults.reset()
    set_emitter(None)
    default_flight_recorder().set_tap(None)


def req(model="m1", **kw) -> InferenceRequest:
    return InferenceRequest(id=f"job-{uuid.uuid4().hex[:8]}", model=model,
                            prompt="hi", priority=Priority.medium, **kw)


async def make_stack(cfg=None):
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    cfg = cfg or fast_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    return bus, registry, scheduler


async def settle(bus):
    """Yield so monitor-spawned tasks (announce/drain) publish, then
    drain the bus."""
    await asyncio.sleep(0)
    await bus.flush()


async def teardown(bus, registry, scheduler, *workers):
    for w in workers:
        await w.stop(announce=False)
    await scheduler.shutdown()
    await registry.shutdown()
    await bus.disconnect()


class _StubRegistry:
    def __init__(self):
        self.applied = []

    def apply_health_state(self, worker_id, state):
        self.applied.append((worker_id, state))


class _StubBus:
    async def publish(self, channel, raw):
        pass


# -- baseline math -----------------------------------------------------------

def test_baseline_ewma_mean_std_and_decay():
    bl = _Baseline(halflife_s=10.0)
    t0 = 1000.0
    for i in range(10):
        bl.observe(1.0, now=t0 + 0.1 * i)
    assert abs(bl.mean() - 1.0) < 1e-6
    assert bl.std() < 1e-3
    # z is judged against max(std, 10% of mean): a steady baseline cannot
    # manufacture infinite z from jitter
    assert 9.5 < bl.zscore(2.0) < 10.5
    assert abs(bl.zscore(1.0)) < 0.1
    # 100 half-lives later the old mass is gone: one observation dominates
    bl.observe(5.0, now=t0 + 1000.0)
    assert abs(bl.mean() - 5.0) < 1e-3


def test_baseline_judged_before_fold(monkeypatch):
    """A regression cannot mask itself by dragging the mean toward it in
    the same call: the anomaly is flagged even though the bad sample also
    folds into the baseline."""
    monkeypatch.setenv("GRIDLLM_HEALTH_MIN_SAMPLES", "1")
    monkeypatch.setenv("GRIDLLM_HEALTH_DEGRADE_STRIKES", "1")
    hm = HealthMonitor(_StubBus(), _StubRegistry(), MetricsRegistry())
    for _ in range(5):
        hm.note_itl("w", 0.01)
    hm.note_itl("w", 10.0)  # flagged out-of-band, folded into next round
    hm.note_canary("w", ok=True, e2e_s=0.0)
    assert hm.state_of("w") == "degraded"
    assert "itl" in hm.snapshot()["workers"]["w"]["reason"]
    assert SIG_ITL in hm.snapshot()["workers"]["w"]["baselines"]


def test_heartbeat_gap_measured_receiver_side(monkeypatch):
    monkeypatch.setenv("GRIDLLM_HEALTH_MIN_SAMPLES", "3")
    monkeypatch.setenv("GRIDLLM_HEALTH_DEGRADE_STRIKES", "1")
    hm = HealthMonitor(_StubBus(), _StubRegistry(), MetricsRegistry())
    for t in range(6):
        hm.note_heartbeat("w", now=1000.0 + t)  # steady 1 s cadence
    hm.note_heartbeat("w", now=1036.0)          # 30 s seizure
    hm.note_canary("w", ok=True, e2e_s=0.0)
    assert hm.state_of("w") == "degraded"
    assert "heartbeat_gap" in hm.snapshot()["workers"]["w"]["reason"]


# -- state machine (sync, no loop) -------------------------------------------

def test_state_machine_round_trip(monkeypatch):
    monkeypatch.setenv("GRIDLLM_HEALTH_DEGRADE_STRIKES", "2")
    monkeypatch.setenv("GRIDLLM_HEALTH_QUARANTINE_STRIKES", "3")
    monkeypatch.setenv("GRIDLLM_HEALTH_PROBATION_PASSES", "2")
    reg = _StubRegistry()
    mr = MetricsRegistry()
    hm = HealthMonitor(_StubBus(), reg, mr)

    hm.note_canary("w", ok=False, e2e_s=0.1)
    assert hm.state_of("w") == "online"  # first strike is not a verdict
    hm.note_canary("w", ok=False, e2e_s=0.1)
    assert hm.state_of("w") == "degraded"
    for _ in range(3):
        hm.note_canary("w", ok=False, e2e_s=0.1)
    assert hm.state_of("w") == "quarantined"
    assert mr.get("gridllm_worker_health_state").value(worker="w") \
        == STATE_CODES["quarantined"]
    # the local registry saw every verdict before any bus echo
    assert reg.applied == [("w", "degraded"), ("w", "quarantined")]

    # clean canaries never resurrect a quarantined worker...
    hm.note_canary("w", ok=True, e2e_s=0.1)
    assert hm.state_of("w") == "quarantined"
    # ...only re-registration does, and only into probation
    hm.note_registered("w")
    assert hm.state_of("w") == "probation"
    hm.note_canary("w", ok=True, e2e_s=0.1)
    hm.note_canary("w", ok=True, e2e_s=0.1)
    assert hm.state_of("w") == "online"
    assert reg.applied[-2:] == [("w", "probation"), ("w", "online")]

    # probation is the last chance: one strike goes straight back
    hm.note_canary("w", ok=False, e2e_s=0.1)
    hm.note_canary("w", ok=False, e2e_s=0.1)      # -> degraded
    for _ in range(3):
        hm.note_canary("w", ok=False, e2e_s=0.1)  # -> quarantined
    hm.note_registered("w")                       # -> probation
    hm.note_canary("w", ok=False, e2e_s=0.1)      # -> quarantined again
    assert hm.state_of("w") == "quarantined"
    assert mr.get("gridllm_health_transitions_total").value(
        state="quarantined") == 3
    assert hm.counts()["quarantined"] == 1


def test_golden_drift_quarantines_from_any_state():
    reg = _StubRegistry()
    hm = HealthMonitor(_StubBus(), reg, MetricsRegistry())
    hm.note_canary("w", ok=True, e2e_s=0.1, drift=True)
    assert hm.state_of("w") == "quarantined"
    assert hm.snapshot()["workers"]["w"]["reason"] == "golden_drift"
    assert reg.applied == [("w", "quarantined")]


def test_health_baseline_fault_site_deafens_detector(monkeypatch):
    monkeypatch.setenv("GRIDLLM_HEALTH_MIN_SAMPLES", "1")
    monkeypatch.setenv("GRIDLLM_HEALTH_DEGRADE_STRIKES", "1")
    hm = HealthMonitor(_StubBus(), _StubRegistry(), MetricsRegistry())
    faults.configure("health.baseline=1.0")
    for _ in range(5):
        hm.note_itl("w", 0.01)
    hm.note_itl("w", 50.0)  # a deaf detector never sees the regression
    hm.note_canary("w", ok=True, e2e_s=100.0)
    assert hm.state_of("w") == "online"
    assert hm.snapshot()["workers"]["w"]["baselines"] == {}


# -- canary tenant exclusion (ISSUE 16 conservation) --------------------------

def test_canary_tenant_excluded_from_engine_ledger():
    before = dict(engine_usage_totals())
    account_engine_usage(build_usage(
        tenant=CANARY_TENANT, model="m1", prompt_tokens=11, output_tokens=7))
    assert dict(engine_usage_totals()) == before
    # a real tenant still lands, so conservation keeps balancing
    account_engine_usage(build_usage(
        tenant="t1", model="m1", prompt_tokens=11, output_tokens=7))
    after = dict(engine_usage_totals())
    assert after.get("prompt", 0) - before.get("prompt", 0) == 11
    assert after.get("output", 0) - before.get("output", 0) == 7


def test_canary_tenant_excluded_from_shard_ledger():
    ua = UsageAccountant(MetricsRegistry(), lru_cap=4)
    ua.account(build_usage(tenant=CANARY_TENANT, model="m1",
                           prompt_tokens=5, output_tokens=3), "completed")
    ua.note_outcome(CANARY_TENANT, "m1", "failed")
    assert ua.token_totals() == {}
    assert ua.snapshot() == {"tenants": {}}
    ua.account(build_usage(tenant="t1", model="m1",
                           prompt_tokens=5, output_tokens=3), "completed")
    assert ua.token_totals() == {"prompt": 5.0, "output": 3.0}


# -- canary probing over the bus ---------------------------------------------

async def test_probe_issue_fault_is_error_never_a_strike():
    bus, registry, scheduler = await make_stack()
    w = FakeWorker(bus, "w1", ["m1"])
    await w.start()
    await bus.flush()
    faults.configure("probe.issue=1.0")
    assert await scheduler.prober.probe_once(
        registry.get_worker("w1"), "m1") == "error"
    assert scheduler.prober.goldens == {}
    assert scheduler.health.state_of("w1") == "online"
    faults.reset()
    assert await scheduler.prober.probe_once(
        registry.get_worker("w1"), "m1") == "pass"
    assert len(scheduler.prober.goldens) == 1
    await teardown(bus, registry, scheduler, w)


async def test_probe_timer_loop_seals_and_passes(monkeypatch):
    monkeypatch.setenv("GRIDLLM_PROBE_INTERVAL_MS", "30")
    bus, registry, scheduler = await make_stack()
    assert scheduler.prober.enabled
    w1 = FakeWorker(bus, "w1", ["m1"])
    w2 = FakeWorker(bus, "w2", ["m1"])
    await w1.start()
    await w2.start()
    await bus.flush()
    for _ in range(200):
        s = scheduler.prober.summary()
        if s["probes"] >= 3 and s["goldens"] >= 1:
            break
        await asyncio.sleep(0.05)
    s = scheduler.prober.summary()
    assert s["probes"] >= 3, s
    assert s["byResult"].get("pass", 0) >= 3, s
    assert s["passRate"] == 1.0, s
    # the probes really were pinned canaries, not regular placements
    served = w1.processed + w2.processed
    assert served and all(j.startswith("canary-") for j in served)
    await teardown(bus, registry, scheduler, w1, w2)


async def test_golden_drift_quarantines_drains_and_opens_incident():
    """The acceptance chain on one bus: seal on a healthy worker, drift
    on a rotted one -> immediate quarantine replicated into the registry,
    a drain request on the worker's job channel, placement exclusion, SLO
    attainment untouched, and a forensics incident naming the worker."""
    bus, registry, scheduler = await make_stack()
    mr = MetricsRegistry()
    store = TimelineStore()
    collector = IncidentCollector(store, member="hq", window_ms=10_000,
                                  registry=mr)
    pub = TimelinePublisher("hq", registry=mr)
    pub.install()
    await pub.start(bus)
    await store.attach(bus)
    wa = FakeWorker(bus, "wA", ["m1"], reply="the golden reply")
    wb = FakeWorker(bus, "wB", ["m1"], reply="silently rotted bytes")
    await wa.start()
    await wb.start()
    await bus.flush()

    drains = []

    async def on_job(_ch, raw):
        msg = json.loads(raw)
        if msg.get("type") == "drain":
            drains.append(msg)

    await bus.subscribe("worker:wB:job", on_job)

    try:
        assert await scheduler.prober.probe_once(
            registry.get_worker("wA"), "m1") == "pass"
        # pinned placement graded wA specifically
        assert wa.processed and wa.processed[0].startswith("canary-")
        assert not wb.processed

        assert await scheduler.prober.probe_once(
            registry.get_worker("wB"), "m1") == "drift"
        assert scheduler.health.state_of("wB") == "quarantined"
        await settle(bus)
        assert registry.get_worker("wB").healthState == "quarantined"
        assert "wB" not in [w.workerId
                            for w in registry.get_available_workers()]
        assert any(m.get("reason") == "quarantine" for m in drains)
        # canary traffic moved neither SLO attainment nor the ledger
        assert scheduler.slo.snapshot()["classes"] == {}
        assert scheduler.usage.token_totals() == {}

        # real traffic routes around the quarantined worker
        result = await scheduler.submit_and_wait(req(), timeout_ms=5000)
        assert result.success and result.workerId == "wA"
        assert result.response.response == "the golden reply"

        # forensics: both incident kinds name the worker
        await pub.flush_once()
        await bus.flush()
        kinds = {(r["kind"], r["key"]) for r in collector.reports()}
        assert ("canary_drift", "wB") in kinds
        assert ("worker_quarantined", "wB") in kinds
    finally:
        await pub.stop()
        await store.detach()
        await teardown(bus, registry, scheduler, wa, wb)


# -- the fast differential: silent slowdown ----------------------------------

async def test_slowed_worker_detected_quarantined_zero_loss(monkeypatch):
    """A worker that silently slows down (nothing fails, heartbeats keep
    beating) regresses against its OWN canary-latency baseline, walks
    online -> degraded -> quarantined, gets a drain request, and every
    client request before, during, and after detection still succeeds
    with the expected bytes — zero client-visible loss."""
    monkeypatch.setenv("GRIDLLM_HEALTH_MIN_SAMPLES", "3")
    monkeypatch.setenv("GRIDLLM_HEALTH_DEGRADE_STRIKES", "1")
    monkeypatch.setenv("GRIDLLM_HEALTH_QUARANTINE_STRIKES", "1")
    monkeypatch.setenv("GRIDLLM_HEALTH_Z_THRESHOLD", "8.0")
    bus, registry, scheduler = await make_stack()
    victim = FakeWorker(bus, "wv", ["m1"], delay_s=0.02)
    peer = FakeWorker(bus, "wp", ["m1"], delay_s=0.02)
    await victim.start()
    await peer.start()
    await bus.flush()

    drains = []

    async def on_job(_ch, raw):
        if json.loads(raw).get("type") == "drain":
            drains.append(raw)

    await bus.subscribe("worker:wv:job", on_job)

    try:
        # train both baselines on healthy latency
        for _ in range(4):
            assert await scheduler.prober.probe_once(
                registry.get_worker("wv"), "m1") == "pass"
            assert await scheduler.prober.probe_once(
                registry.get_worker("wp"), "m1") == "pass"
        assert scheduler.health.state_of("wv") == "online"

        victim.delay_s = 0.5  # the silent regression: 25x slower
        assert await scheduler.prober.probe_once(
            registry.get_worker("wv"), "m1") == "pass"  # bytes still right
        assert scheduler.health.state_of("wv") == "degraded"
        # degraded workers stay in rotation (penalized, not excluded)
        assert "wv" in [w.workerId
                        for w in registry.get_available_workers()]
        # the EWMA folded the first bad round in (it adapts to honest
        # drift); only a STILL-worsening worker keeps striking
        victim.delay_s = 2.5
        assert await scheduler.prober.probe_once(
            registry.get_worker("wv"), "m1") == "pass"
        assert scheduler.health.state_of("wv") == "quarantined"
        await settle(bus)
        assert registry.get_worker("wv").healthState == "quarantined"
        assert "wv" not in [w.workerId
                            for w in registry.get_available_workers()]
        assert drains, "quarantine never requested a drain"

        # zero loss: concurrent real traffic all resolves with the right
        # bytes, served by the healthy peer
        results = await asyncio.gather(
            *[scheduler.submit_and_wait(req(), timeout_ms=8000)
              for _ in range(4)])
        assert all(r.success for r in results)
        assert all(r.response.response == "canned response" for r in results)
        assert all(r.workerId == "wp" for r in results)
        assert not [j for j in victim.processed if j.startswith("job-")]

        m = scheduler.metrics
        assert m.get("gridllm_worker_health_state").value(worker="wv") == 3
        assert m.get("gridllm_health_transitions_total").value(
            state="quarantined") >= 1
    finally:
        await teardown(bus, registry, scheduler, victim, peer)


# -- probation re-entry + placement preference --------------------------------

async def test_probation_reentry_preference_and_readmission(monkeypatch):
    monkeypatch.setenv("GRIDLLM_HEALTH_DEGRADE_STRIKES", "1")
    monkeypatch.setenv("GRIDLLM_HEALTH_QUARANTINE_STRIKES", "1")
    monkeypatch.setenv("GRIDLLM_HEALTH_PROBATION_PASSES", "2")
    bus, registry, scheduler = await make_stack()
    wa = FakeWorker(bus, "wA", ["m1"])
    wb = FakeWorker(bus, "wB", ["m1"])
    await wa.start()
    await wb.start()
    await bus.flush()
    try:
        scheduler.health.note_canary("wB", ok=False, e2e_s=0.1)
        scheduler.health.note_canary("wB", ok=False, e2e_s=0.1)
        assert scheduler.health.state_of("wB") == "quarantined"
        await settle(bus)
        assert registry.get_worker("wB").healthState == "quarantined"

        # operator restarts the worker: re-registration is the ONLY exit,
        # and it lands in probation — the verdict survives the re-register
        await wb.register()
        await bus.flush()
        assert scheduler.health.state_of("wB") == "probation"
        assert registry.get_worker("wB").healthState == "probation"

        # probation workers dodge placement while alternatives exist
        for _ in range(3):
            r = await scheduler.submit_and_wait(req(), timeout_ms=5000)
            assert r.success and r.workerId == "wA"

        # clean canaries keep flowing to probation workers and readmit
        assert await scheduler.prober.probe_once(
            registry.get_worker("wB"), "m1") == "pass"
        assert await scheduler.prober.probe_once(
            registry.get_worker("wB"), "m1") == "pass"
        assert scheduler.health.state_of("wB") == "online"
        await settle(bus)
        assert registry.get_worker("wB").healthState == "online"
    finally:
        await teardown(bus, registry, scheduler, wa, wb)


# -- surfaces: admin endpoint + fleet view ------------------------------------

async def test_admin_health_fleet_endpoint():
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.gateway.app import create_app
    from gridllm_tpu.utils.config import Config

    bus, registry, scheduler = await make_stack()
    config = Config()
    config.scheduler = fast_config()
    app = create_app(bus, registry, scheduler, config)
    client = TestClient(TestServer(app))
    await client.start_server()
    w = FakeWorker(bus, "w1", ["m1"])
    await w.start()
    await bus.flush()
    try:
        assert await scheduler.prober.probe_once(
            registry.get_worker("w1"), "m1") == "pass"
        body = await (await client.get("/admin/health/fleet")).json()
        assert body["health"]["workers"]["w1"]["state"] == "online"
        assert body["health"]["counts"]["online"] == 1
        assert body["canary"]["probes"] >= 1
        assert body["canary"]["goldens"] == 1
        # /health/workers carries the verdict per worker too
        workers = await (await client.get("/health/workers")).json()
        assert workers["workers"][0]["healthState"] == "online"
    finally:
        await client.close()
        await teardown(bus, registry, scheduler, w)


async def test_fleet_view_merges_health():
    from gridllm_tpu.controlplane.status import FleetView, StatusPublisher

    from .test_controlplane import make_fleet, stop_fleet

    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    shards, gws = await make_fleet(bus)
    view = FleetView(bus, gws[0].metrics, stale_after_ms=5000)
    await view.start()
    try:
        shards[0].scheduler.health.note_canary("wQ", ok=True, e2e_s=0.01)
        pubs = [StatusPublisher(bus, sh.scheduler, "shard", sh.member_id,
                                100, lease=sh.lease) for sh in shards]
        for p in pubs:
            await p.publish_once()
        await bus.flush()
        merged = view.merged_health()
        assert merged["shard-0"]["health"]["workers"]["wQ"]["state"] \
            == "online"
        assert merged["shard-0"]["canary"]["enabled"] is False
        assert "shard-1" in merged
    finally:
        await view.stop()
        await stop_fleet(shards, gws)
        await bus.disconnect()


# -- the slow differential: real engines, silent sampler rot ------------------

@pytest.mark.slow
async def test_sampler_rot_drifts_golden_and_quarantines(monkeypatch):
    """Chaos differential with REAL engines over a REAL broker: a child
    worker whose sampler is silently perturbed (same engineConfigHash,
    same latency, wrong bytes) registers next to a healthy in-process
    peer. The peer seals the golden; the rotted worker's FIRST canary
    drifts -> immediate quarantine, drain request, and the verdict
    survives the worker's own drain re-register. Client traffic keeps
    matching the healthy reference byte-for-byte — zero token loss."""
    from gridllm_tpu.bus import create_bus
    from gridllm_tpu.bus.broker import GridBusBroker
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.utils.config import SchedulerConfig, WorkerConfig
    from gridllm_tpu.worker.service import WorkerService

    # the victim's first canary pays its first-compile cost
    monkeypatch.setenv("GRIDLLM_PROBE_TIMEOUT_MS", "180000")

    broker = GridBusBroker()
    await broker.start(port=0)
    url = f"resp://127.0.0.1:{broker.port}"
    bus = create_bus(url)
    await bus.connect()
    cfg = SchedulerConfig(
        worker_heartbeat_timeout_ms=600,
        worker_cleanup_interval_ms=100,
        connection_monitor_interval_ms=100,
        quick_disconnect_window_ms=400,
        orphan_assign_threshold_ms=200,
        job_timeout_ms=180_000,
        retry_attempts=2,
        retry_delay_ms=50,
        sweep_interval_ms=100,
    )
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()

    mr = MetricsRegistry()
    store = TimelineStore()
    collector = IncidentCollector(store, member="hq", window_ms=30_000,
                                  registry=mr)
    pub = TimelinePublisher("hq", registry=mr)
    pub.install()
    await pub.start(bus)
    await store.attach(bus)

    def gen_req(rid: str) -> InferenceRequest:
        return InferenceRequest(
            id=rid, model="tiny-llama", prompt="fleet health reference",
            options={"temperature": 0, "num_predict": 8, "seed": 3},
            priority=Priority.medium)

    env = {**os.environ, "PYTHONPATH": str(DRIFT_CHILD.parent.parent)}
    env.pop("XLA_FLAGS", None)
    child = None
    peer = WorkerService(
        bus, {"tiny-llama": InferenceEngine(EngineConfig(
            model="tiny-llama", max_slots=2, page_size=8, num_pages=32,
            max_pages_per_slot=4, prefill_buckets=(16, 32),
        ))},
        WorkerConfig(worker_id="health-peer", heartbeat_interval_ms=150,
                     resource_monitor_interval_ms=500),
        stream_flush_ms=5,
    )
    try:
        await peer.start()
        for _ in range(200):
            if registry.get_workers_with_model("tiny-llama"):
                break
            await asyncio.sleep(0.1)

        # healthy reference bytes + golden seal, both on the peer
        ref = await scheduler.submit_and_wait(
            gen_req(f"job-{uuid.uuid4().hex[:8]}"), timeout_ms=180_000)
        assert ref.success and ref.response.response
        ref_text = ref.response.response
        assert await scheduler.prober.probe_once(
            registry.get_worker("health-peer"), "tiny-llama") == "pass"
        assert len(scheduler.prober.goldens) == 1

        child = subprocess.Popen(
            [sys.executable, str(DRIFT_CHILD), str(broker.port),
             "health-victim"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for _ in range(1200):
            if registry.get_worker("health-victim") is not None:
                break
            await asyncio.sleep(0.1)
        assert registry.get_worker("health-victim") is not None, (
            child.stdout.read() if child.poll() is not None else
            "victim never registered")

        # same model, same engineConfigHash -> same golden key; the rotted
        # sampler makes the FIRST canary drift, quarantining immediately
        assert await scheduler.prober.probe_once(
            registry.get_worker("health-victim"), "tiny-llama") == "drift"
        assert scheduler.health.state_of("health-victim") == "quarantined"
        assert len(scheduler.prober.goldens) == 1  # never re-sealed
        assert scheduler.prober.summary()["byResult"].get("drift") == 1

        # quarantine drains the worker; the drain's own re-register must
        # NOT launder the verdict (registry preserves healthState)
        drained = False
        for _ in range(150):
            w = registry.get_worker("health-victim")
            if w is not None and w.status == "draining":
                drained = True
                break
            await asyncio.sleep(0.1)
        assert drained, "victim never started draining"
        assert registry.get_worker("health-victim").healthState \
            == "quarantined"

        # zero token loss: traffic keeps matching the healthy reference
        for _ in range(3):
            r = await scheduler.submit_and_wait(
                gen_req(f"job-{uuid.uuid4().hex[:8]}"), timeout_ms=60_000)
            assert r.success and r.workerId == "health-peer"
            assert r.response.response == ref_text
        assert "health-victim" not in [
            w.workerId for w in registry.get_available_workers()]

        # forensics incidents name the victim
        await pub.flush_once()
        deadline = asyncio.get_running_loop().time() + 5
        while (collector.count() < 2
               and asyncio.get_running_loop().time() < deadline):
            await asyncio.sleep(0.1)
        kinds = {(r["kind"], r["key"]) for r in collector.reports()}
        assert ("canary_drift", "health-victim") in kinds
        assert ("worker_quarantined", "health-victim") in kinds
    finally:
        if child is not None and child.poll() is None:
            child.kill()
        await pub.stop()
        await store.detach()
        await peer.stop()
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()
        await broker.stop()
