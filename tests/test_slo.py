"""ISSUE 2 interpretation layer: SLO engine (attainment / burn rates /
goodput), hang watchdog (per-phase detection, requeue, auto dump), flight
recorder (bounded rings, dump artifact), and the /admin/slo + /admin/dump
gateway routes — including the acceptance check that /admin/slo agrees
with the /metrics gauges."""

import asyncio
import json

from aiohttp.test_utils import TestClient, TestServer

from gridllm_tpu.bus.memory import InMemoryBus
from gridllm_tpu.gateway.app import create_app
from gridllm_tpu.obs import (
    FlightRecorder,
    MetricsRegistry,
    SLOEngine,
    build_dump,
    classify_request,
    default_flight_recorder,
)
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.config import (
    Config,
    SLOClassConfig,
    SLOConfig,
    WatchdogConfig,
)
from gridllm_tpu.utils.types import InferenceRequest

from .helpers import FakeWorker, fast_config

# ---------------------------------------------------------------------------
# SLO engine unit
# ---------------------------------------------------------------------------


def _slo(target=0.9, **objectives) -> SLOEngine:
    cfg = SLOConfig(classes={
        "interactive": SLOClassConfig(target=target, **objectives)})
    return SLOEngine(cfg, MetricsRegistry())


def test_classify_request():
    assert classify_request(InferenceRequest(
        id="a", model="m", prompt="x", stream=True)) == "interactive"
    assert classify_request(InferenceRequest(
        id="b", model="m", prompt="x")) == "batch"
    assert classify_request(InferenceRequest(
        id="c", model="m", input=["x"],
        metadata={"requestType": "embedding"})) == "embedding"


def test_slo_judgment_and_attainment():
    s = _slo(ttft_ms=1000, itl_ms=100, e2e_ms=10_000)
    assert s.record("interactive", ttft_s=0.5, itl_s=0.05, e2e_s=2.0,
                    tokens=10)
    assert not s.record("interactive", ttft_s=2.0, itl_s=0.05, e2e_s=2.0)
    assert not s.record("interactive", ok=False, e2e_s=1.0)
    # a missing measurement is not a violation (one-token reply has no ITL)
    assert s.record("interactive", ttft_s=0.5, e2e_s=2.0, tokens=1)
    snap = s.snapshot()["classes"]["interactive"]
    assert snap["requests"] == 4
    assert snap["withinSlo"] == 2
    assert snap["attainment"] == 0.5
    assert snap["violations"] == {"ttft": 1, "error": 1}


def test_slo_unknown_class_counts_without_objectives():
    s = _slo(e2e_ms=1)
    assert s.record("mystery", e2e_s=999.0)  # no objectives → within
    assert s.snapshot()["classes"]["mystery"]["attainment"] == 1.0


def test_burn_rate_windows():
    s = _slo(target=0.9, e2e_ms=1000)
    now = 1_000_000.0
    # 4 old requests (one bad), then 2 recent (both bad): the short window
    # must see 100% violation rate, the long window the blended rate
    for i in range(4):
        s.record("interactive", e2e_s=2.0 if i == 0 else 0.1,
                 now=now - 500)
    for _ in range(2):
        s.record("interactive", e2e_s=2.0, now=now - 10)
    import pytest

    st = s._classes["interactive"]
    s.config.windows_s = [1, 60, 3600]
    rates = s._burn_rates_locked(st, 0.9, now)  # one pass, all windows
    # budget = 1 - 0.9 = 0.1 → burn = violation_rate / 0.1
    assert rates[60] == pytest.approx(10.0)     # 2/2 bad in window
    assert rates[3600] == pytest.approx(5.0)    # 3/6 bad in window
    assert rates[1] == 0.0                      # empty window


def test_goodput_and_waste_accounting():
    s = _slo(e2e_ms=1000)
    s.record("interactive", e2e_s=0.5, tokens=100)   # good
    s.record("interactive", e2e_s=5.0, tokens=40)    # violates → not goodput
    s.record_waste(25, reason="duplicate_execution")
    snap = s.snapshot()["goodput"]
    assert snap["tokensTotal"] == 140
    assert snap["tokensWithinSlo"] == 100
    assert snap["wastedTokens"] == {"duplicate_execution": 25}
    text = s.metrics.render()
    assert ('gridllm_goodput_tokens_total{slo_class="interactive"} 100'
            in text)
    assert ('gridllm_goodput_wasted_tokens_total'
            '{reason="duplicate_execution"} 25') in text


def test_slo_gauges_agree_with_snapshot():
    s = _slo(target=0.5, e2e_ms=1000)
    s.record("interactive", e2e_s=0.1, tokens=5)
    s.record("interactive", e2e_s=9.9, tokens=5)
    text = s.metrics.render()  # collector runs at render
    snap = s.snapshot()
    att = snap["classes"]["interactive"]["attainment"]
    assert f'gridllm_slo_attainment_ratio{{slo_class="interactive"}} {att}' \
        in text
    assert 'gridllm_slo_burn_rate{slo_class="interactive",window="300s"}' \
        in text
    assert "gridllm_goodput_ratio 0.5" in text


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_bounds_and_eviction_counts():
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.record("engine", "block", gen=i)
    snap = rec.snapshot()
    assert [e["gen"] for e in snap["rings"]["engine"]] == [6, 7, 8, 9]
    assert snap["evicted"] == {"engine": 6}  # truncation is never silent


def test_flight_recorder_auto_dumps_bounded():
    rec = FlightRecorder(capacity=4, max_auto_dumps=2)
    for i in range(3):
        rec.add_auto_dump({"reason": f"r{i}"})
    assert [d["reason"] for d in rec.auto_dumps()] == ["r1", "r2"]


def test_build_dump_without_scheduler():
    rec = FlightRecorder(capacity=4)
    rec.record("bus", "reconnect", attempt=1)
    artifact = build_dump(recorder=rec, reason="unit")
    assert artifact["reason"] == "unit"
    assert artifact["flightRecorder"]["rings"]["bus"][0]["event"] == \
        "reconnect"
    assert "engines" in artifact and "autoDumps" in artifact
    json.dumps(artifact)  # must be JSON-able end to end


# ---------------------------------------------------------------------------
# stack integration: /admin/slo + /admin/dump + watchdog
# ---------------------------------------------------------------------------


async def _make_stack(slo_config=None, watchdog_config=None):
    bus = InMemoryBus(key_prefix="G:")
    await bus.connect()
    cfg = fast_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg, slo_config=slo_config,
                             watchdog_config=watchdog_config)
    await registry.initialize()
    await scheduler.initialize()
    app = create_app(bus, registry, scheduler, Config(scheduler=cfg))
    client = TestClient(TestServer(app))
    await client.start_server()
    return client, bus, registry, scheduler


async def _teardown(client, bus, registry, scheduler, *workers):
    for w in workers:
        await w.stop(announce=False)
    await client.close()
    await scheduler.shutdown()
    await registry.shutdown()
    await bus.disconnect()


async def test_admin_slo_agrees_with_metrics_after_requests():
    client, bus, registry, scheduler = await _make_stack()
    w = FakeWorker(bus, "w1", ["m1"], stream_tokens=["a", "b", "c"])
    await w.start()
    await bus.flush()

    for _ in range(2):
        resp = await client.post("/ollama/api/generate",
                                 json={"model": "m1", "prompt": "go"})
        assert resp.status == 200
        await resp.text()
    await bus.flush()

    def fmt(v):  # the exposition's number formatting (metrics._format_value)
        return str(int(v)) if float(v).is_integer() else repr(float(v))

    body = await (await client.get("/admin/slo")).json()
    inter = body["classes"]["interactive"]
    assert inter["requests"] == 2
    assert inter["attainment"] is not None
    text = await (await client.get("/metrics")).text()
    assert 'gridllm_slo_requests_total{slo_class="interactive"} 2' in text
    assert (f'gridllm_slo_attainment_ratio{{slo_class="interactive"}} '
            f'{fmt(inter["attainment"])}') in text
    # goodput tokens agree too
    assert (f'gridllm_slo_tokens_total{{slo_class="interactive"}} '
            f'{inter["tokens"]}') in text
    await _teardown(client, bus, registry, scheduler, w)


async def test_timeout_is_an_slo_violation():
    client, bus, registry, scheduler = await _make_stack()
    w = FakeWorker(bus, "w1", ["m1"], delay_s=30)
    await w.start()
    await bus.flush()
    from gridllm_tpu.scheduler.scheduler import JobTimeoutError

    try:
        await scheduler.submit_and_wait(
            InferenceRequest(id="slo-t1", model="m1", prompt="x"),
            timeout_ms=200)
        raise AssertionError("expected timeout")
    except JobTimeoutError:
        pass
    snap = scheduler.slo.snapshot()["classes"]["batch"]
    assert snap["requests"] == 1
    assert snap["violations"].get("error") == 1
    assert snap["attainment"] == 0.0
    await _teardown(client, bus, registry, scheduler, w)


async def test_admin_dump_artifact_sections():
    client, bus, registry, scheduler = await _make_stack()
    w = FakeWorker(bus, "w1", ["m1"], stream_tokens=["a"])
    await w.start()
    await bus.flush()
    resp = await client.post("/ollama/api/generate",
                             json={"model": "m1", "prompt": "go"})
    assert resp.status == 200
    await resp.text()
    await bus.flush()

    body = await (await client.get("/admin/dump")).json()
    assert body["reason"] == "on_demand"
    assert "rings" in body["flightRecorder"]
    assert "interactive" in body["slo"]["classes"]
    assert body["registry"]["counts"]["total"] == 1
    assert body["scheduler"]["stats"]["totalJobsCompleted"] == 1
    await _teardown(client, bus, registry, scheduler, w)


class WedgedWorker(FakeWorker):
    """Streams one token, then wedges mid-decode WITHOUT exiting: the
    heartbeat keeps beating, so only the watchdog can tell it is stuck."""

    async def _execute(self, assignment):
        self.current_jobs += 1
        from gridllm_tpu.utils.types import StreamChunk, iso_now

        await self.bus.publish(f"job:stream:{assignment.jobId}", StreamChunk(
            id=assignment.jobId, model=assignment.request.model,
            created_at=iso_now(), response="x", done=False,
        ).model_dump_json())
        try:
            await asyncio.sleep(3600)  # wedged forever
        finally:
            self.current_jobs -= 1


async def test_watchdog_detects_decode_stall_and_requeues():
    recorder = default_flight_recorder()
    recorder.clear()
    wd = WatchdogConfig(interval_ms=50, decode_stall_ms=250,
                        dispatch_deadline_ms=60_000, requeue=True)
    client, bus, registry, scheduler = await _make_stack(watchdog_config=wd)
    wedged = WedgedWorker(bus, "w-wedged", ["m1"])
    await wedged.start()
    await bus.flush()

    resp_task = asyncio.create_task(client.post(
        "/ollama/api/generate", json={"model": "m1", "prompt": "go"}))
    # wait until the watchdog flags the stall and requeues with reason hang
    for _ in range(100):
        await asyncio.sleep(0.05)
        if scheduler.metrics.get("gridllm_hangs_total").value(
                phase="decode-step"):
            break
    assert scheduler.metrics.get("gridllm_hangs_total").value(
        phase="decode-step") >= 1

    # the job was cancelled on the wedged worker and requeued (orphan path,
    # reason hang) — a healthy worker then serves it to completion.
    # Polled: hang handling now yields between detection and requeue (the
    # decode-step auto profiler capture runs via to_thread), so the
    # counter can be visible a beat before the cancellation publish.
    for _ in range(100):
        await bus.flush()
        if wedged.cancelled:
            break
        await asyncio.sleep(0.05)
    assert wedged.cancelled  # cancellation delivered
    healthy = FakeWorker(bus, "w-ok", ["m2", "m1"],
                         stream_tokens=["a", "b"])
    await healthy.start()
    await bus.flush()
    resp = await asyncio.wait_for(resp_task, 15)
    assert resp.status == 200
    await resp.text()
    assert healthy.processed  # served by the replacement

    # auto dump names the hung request, phase, and worker
    dumps = recorder.auto_dumps()
    hang_dumps = [d for d in dumps if d["reason"].startswith("hang:")]
    assert hang_dumps, [d["reason"] for d in dumps]
    hang = hang_dumps[0]["hang"]
    assert hang["phase"] == "decode-step"
    assert hang["worker"] == "w-wedged"
    assert hang["requestId"]
    # the hang marker landed on the trace
    spans = scheduler.tracer.export(hang["requestId"])
    assert any(s["name"] == "watchdog.hang" for s in spans)
    text = scheduler.metrics.render()
    assert 'gridllm_hangs_total{phase="decode-step"}' in text
    await _teardown(client, bus, registry, scheduler, wedged, healthy)


async def test_watchdog_flags_queue_hang_without_requeue():
    wd = WatchdogConfig(interval_ms=50, queue_deadline_ms=100, requeue=True)
    client, bus, registry, scheduler = await _make_stack(watchdog_config=wd)
    # no worker serves the model → the job sits queued
    req = InferenceRequest(id="q-hang", model="nope", prompt="x")
    await scheduler.add_job(req)
    for _ in range(60):
        await asyncio.sleep(0.05)
        if scheduler.metrics.get("gridllm_hangs_total").value(phase="queue"):
            break
    assert scheduler.metrics.get(
        "gridllm_hangs_total").value(phase="queue") == 1
    # still queued — queue hangs are diagnosis-only
    assert scheduler.get_queue_position("q-hang") is not None
    # flagged once, not once per sweep
    await asyncio.sleep(0.3)
    assert scheduler.metrics.get(
        "gridllm_hangs_total").value(phase="queue") == 1
    await _teardown(client, bus, registry, scheduler)


async def test_worker_crash_triggers_auto_dump():
    recorder = default_flight_recorder()
    recorder.clear()
    client, bus, registry, scheduler = await _make_stack()
    w = FakeWorker(bus, "w-crash", ["m1"], heartbeat_interval_s=0.1)
    await w.start()
    await bus.flush()
    await w.die()  # abrupt: no unregister, heartbeat key deleted
    for _ in range(100):
        await asyncio.sleep(0.05)
        if any(d["reason"].startswith("worker_crash:")
               for d in recorder.auto_dumps()):
            break
    crash = [d for d in recorder.auto_dumps()
             if d["reason"].startswith("worker_crash:")]
    assert crash, [d["reason"] for d in recorder.auto_dumps()]
    assert crash[0]["crash"]["worker"] == "w-crash"
    await _teardown(client, bus, registry, scheduler)
