"""Fleet timeline, HLC, and incident forensics tests (ISSUE 17).

Covers the HLC semantics the causal timeline rests on (merge
monotonicity, cross-member happens-before through a bus round-trip,
member-id tie-breaking, clock-skew injection), the publisher's
never-block backpressure contract, the additive critical-path
decomposition, the fleet store/forensics surfaces, and THE acceptance
gate: SIGKILL-style death of the owning scheduler shard produces — from
one ``GET /admin/incidents`` on a surviving member — a causally ordered
incident report stitching events from ≥ 3 distinct members, with every
bus edge ordered send-before-receive despite injected clock skew."""

import asyncio
import json
import time

from gridllm_tpu.bus import InMemoryBus
from gridllm_tpu.obs import MetricsRegistry
from gridllm_tpu.obs.flightrec import default_flight_recorder
from gridllm_tpu.obs.forensics import IncidentCollector
from gridllm_tpu.obs.timeline import (
    HLC,
    HLCStamp,
    TimelinePublisher,
    TimelineStore,
    critical_path,
    default_clock,
    encode_hlc,
    set_emitter,
    split_hlc,
    stamp_key,
)

from .test_controlplane import job_for_shard, make_fleet, req, stop_fleet
from .helpers import FakeWorker


def _cleanup_emitter():
    set_emitter(None)
    default_flight_recorder().set_tap(None)


# -- HLC semantics -----------------------------------------------------------

def test_hlc_tick_strictly_monotonic():
    clock = HLC("a")
    stamps = [clock.tick() for _ in range(100)]
    for prev, cur in zip(stamps, stamps[1:]):
        assert cur > prev


def test_hlc_tick_monotonic_under_frozen_clock():
    # a frozen physical clock still yields strictly increasing stamps
    # through the logical counter
    clock = HLC("a", now_fn=lambda: 1000.0)
    stamps = [clock.tick() for _ in range(10)]
    assert all(s.wall_ms == 1_000_000 for s in stamps)
    assert [s.logical for s in stamps] == list(range(10))


def test_hlc_update_happens_after_remote_and_local():
    a, b = HLC("a"), HLC("b")
    for _ in range(50):
        remote = a.tick()
        before = b.peek()
        merged = b.update(remote)
        assert merged > remote
        assert merged > before


def test_hlc_member_tie_break_is_deterministic():
    s1 = HLCStamp(1000, 3, "member-a")
    s2 = HLCStamp(1000, 3, "member-b")
    assert s1 < s2  # same instant: member id orders, deterministically
    assert sorted([s2, s1]) == [s1, s2]


def test_hlc_clock_skew_preserves_causal_order():
    """Member A's physical clock runs 90 s behind B's: a message A→B
    then B→A must still order send < receive at every hop."""
    t0 = time.time()
    a = HLC("a", now_fn=lambda: t0 - 90.0)
    b = HLC("b", now_fn=lambda: t0)
    send_ab = a.tick()
    recv_ab = b.update(send_ab)
    assert recv_ab > send_ab
    send_ba = b.tick()
    recv_ba = a.update(send_ba)
    assert recv_ba > send_ba
    # and A's clock has absorbed B's future time: a local event on A
    # now orders after the whole exchange even though A's wall lags
    assert a.tick() > recv_ba


def test_hlc_stamp_codec_round_trip():
    s = HLCStamp(123456, 7, "shard-1")
    assert HLCStamp.parse(s.encode()) == s
    assert HLCStamp.from_list(s.to_list()) == s
    assert HLCStamp.from_list("garbage") is None
    framed = encode_hlc(s, '{"jobId": "x"}')
    stamp, body = split_hlc(framed)
    assert stamp == s and body == '{"jobId": "x"}'
    # unframed messages pass through untouched (rolling upgrades, tests)
    assert split_hlc('{"plain": 1}') == (None, '{"plain": 1}')


async def test_bus_round_trip_orders_send_before_receive():
    """A lifecycle publish through a real bus emits a bus.send and a
    bus.recv edge with send < recv under HLC, tagged with the request id
    parsed from the payload."""
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    pub = TimelinePublisher("m1", registry=MetricsRegistry())
    pub.install()
    try:
        got = asyncio.Event()

        async def handler(_ch, _msg):
            got.set()

        sub = await bus.subscribe("job:completed", handler)
        await bus.publish("job:completed", json.dumps({"jobId": "job-7"}))
        await bus.flush()
        await asyncio.wait_for(got.wait(), 2.0)
        await sub.unsubscribe()
        events = list(pub._q)
        sends = [e for e in events if e["name"] == "bus.send"]
        recvs = [e for e in events if e["name"] == "bus.recv"]
        assert sends and recvs
        assert sends[0]["requestId"] == "job-7"
        assert recvs[0]["requestId"] == "job-7"
        assert stamp_key(sends[0]) < stamp_key(recvs[0])
    finally:
        await pub.stop()
        _cleanup_emitter()
        await bus.disconnect()


async def test_handler_sees_unframed_payload():
    """The HLC frame is transport detail: subscribers receive the exact
    payload the publisher passed in."""
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    seen = []

    async def handler(_ch, msg):
        seen.append(msg)

    sub = await bus.subscribe("job:completed", handler)
    await bus.publish("job:completed", '{"jobId": "j1"}')
    await bus.flush()
    assert seen == ['{"jobId": "j1"}']
    await sub.unsubscribe()
    await bus.disconnect()


# -- publisher backpressure ---------------------------------------------------

def test_publisher_never_blocks_and_drops_oldest():
    reg = MetricsRegistry()
    pub = TimelinePublisher("m1", queue_capacity=8, registry=reg)
    t0 = time.monotonic()
    for i in range(10_000):
        pub.emit("scheduler.retry", request_id=f"j{i}")
    elapsed = time.monotonic() - t0
    # the emit path is a deque append behind a lock — wedging the bus
    # (no flush task running at all here) costs events, never latency
    assert elapsed < 1.0
    assert pub.pending() == 8
    assert pub._dropped.value(member="m1") == 10_000 - 8
    # oldest dropped, newest retained
    assert [e["requestId"] for e in pub._q] == [
        f"j{i}" for i in range(9992, 10_000)]


async def test_publisher_counts_failed_flush_as_dropped():
    class WedgedBus:
        async def publish(self, *_a, **_k):
            raise ConnectionError("broker down")

    reg = MetricsRegistry()
    pub = TimelinePublisher("m1", registry=reg)
    pub._bus = WedgedBus()
    pub.emit("scheduler.retry", request_id="j1")
    assert await pub.flush_once() == 0
    assert pub.pending() == 0  # batch not requeued — bound holds
    assert pub._dropped.value(member="m1") == 1


async def test_flightrec_tap_maps_record_sites_to_events():
    pub = TimelinePublisher("gw-0", registry=MetricsRegistry())
    pub.install()
    try:
        rec = default_flight_recorder()
        rec.record("scheduler", "retry", job="job-1", attempt=2,
                   error="boom")
        rec.record("worker", "started", worker="w-9", models=["m1"])
        events = {e["name"]: e for e in pub._q}
        assert events["scheduler.retry"]["requestId"] == "job-1"
        assert events["scheduler.retry"]["member"] == "gw-0"
        assert events["scheduler.retry"]["fields"]["attempt"] == 2
        # worker-side subsystems attribute to the worker id
        assert events["worker.started"]["member"] == "w-9"
    finally:
        await pub.stop()
        _cleanup_emitter()


# -- timeline store -----------------------------------------------------------

def _ev(name, wall, logical, member, rid=None):
    ev = {"name": name, "member": member,
          "stamp": [wall, logical, member]}
    if rid:
        ev["requestId"] = rid
    return ev


def test_store_slices_in_hlc_order():
    store = TimelineStore()
    store.ingest(_ev("b", 2000, 0, "m2", rid="r1"))
    store.ingest(_ev("c", 2000, 1, "m1", rid="r1"))
    store.ingest(_ev("a", 1000, 5, "m1", rid="r1"))
    store.ingest(_ev("x", 1500, 0, "m1", rid="other"))
    assert [e["name"] for e in store.slice("r1")] == ["a", "b", "c"]
    assert store.slice("missing") == []
    window = store.window(1500, 2000)
    assert [e["name"] for e in window] == ["x", "b", "c"]


def test_store_bounds_request_index():
    store = TimelineStore(capacity=100, max_requests=3)
    for i in range(5):
        store.ingest(_ev("e", 1000 + i, 0, "m", rid=f"r{i}"))
    assert store.slice("r0") == [] and store.slice("r1") == []
    assert len(store.slice("r4")) == 1


async def test_store_ingests_published_batches():
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    pub = TimelinePublisher("m1", registry=MetricsRegistry())
    store = TimelineStore()
    await store.attach(bus)
    await pub.start(bus)
    try:
        pub.emit("scheduler.retry", request_id="j1", fields={"attempt": 1})
        assert await pub.flush_once() == 1
        await bus.flush()
        sl = store.slice("j1")
        assert len(sl) == 1 and sl[0]["name"] == "scheduler.retry"
    finally:
        await pub.stop()
        await store.detach()
        _cleanup_emitter()
        await bus.disconnect()


# -- incident forensics -------------------------------------------------------

def test_incident_collector_triggers_and_debounces():
    store = TimelineStore()
    inc = IncidentCollector(store, member="gw-0", window_ms=5000,
                            registry=MetricsRegistry())
    base = int(time.time() * 1000)
    store.ingest(_ev("scheduler.retry", base - 10, 0, "s0", rid="j1"))
    store.ingest(_ev("scheduler.hang", base, 0, "s0", rid="j1"))
    # a retrigger for the same subject inside the window is the SAME
    # incident, not a report flood
    store.ingest(_ev("scheduler.hang", base + 100, 0, "s0", rid="j1"))
    assert inc.count() == 1
    reports = inc.reports(now_ms=base + 10_000)
    assert len(reports) == 1
    rep = reports[0]
    assert rep["kind"] == "watchdog_hang" and rep["complete"]
    # the causal window captured the pre-trigger context event too
    assert [e["name"] for e in rep["events"]][:2] == [
        "scheduler.retry", "scheduler.hang"]
    # a second subject is a second incident
    store.ingest(_ev("scheduler.hang", base, 0, "s0", rid="j2"))
    assert inc.count() == 2


def test_incident_report_incomplete_until_window_elapses():
    store = TimelineStore()
    inc = IncidentCollector(store, member="gw-0", window_ms=5000,
                            registry=MetricsRegistry())
    base = int(time.time() * 1000)
    store.ingest(_ev("bus.failover", base, 0, "s0"))
    assert not inc.reports(now_ms=base + 100)[0]["complete"]
    assert inc.reports(now_ms=base + 5001)[0]["complete"]


# -- critical-path decomposition ----------------------------------------------

def _span(name, start, end, **meta):
    return {"name": name, "source": "t", "start": start, "end": end,
            "durationMs": (end - start) * 1000, "meta": meta or None}


def test_critical_path_segments_are_additive():
    spans = [
        _span("gateway.request", 0.0, 10.0),
        _span("queue.wait", 0.5, 2.0),
        _span("worker.execute", 2.5, 9.5),
        _span("engine.prefill", 3.0, 4.0),
        _span("engine.decode", 4.0, 9.0, engineNs=3.0e9),
        _span("kvx.send", 6.0, 6.5),  # migration interrupts decode
    ]
    seg = critical_path(spans)
    assert seg is not None
    total = sum(seg[k] for k in (
        "queue_wait", "dispatch", "prefill", "decode_device",
        "decode_host_stall", "migration", "suspend_resume"))
    assert abs(total - seg["e2e"]) < 1e-9
    assert abs(seg["e2e"] - 10.0) < 1e-9
    assert abs(seg["queue_wait"] - 1.5) < 1e-9
    assert abs(seg["prefill"] - 1.0) < 1e-9
    assert abs(seg["migration"] - 0.5) < 1e-9  # wins over decode overlap
    decode_cov = seg["decode_device"] + seg["decode_host_stall"]
    assert abs(decode_cov - 4.5) < 1e-9  # 5.0 minus the migration bite
    assert abs(seg["decode_device"] - 3.0) < 1e-9  # engineNs bound
    assert abs(seg["decode_host_stall"] - 1.5) < 1e-9


def test_critical_path_gap_inside_execution_is_suspend_resume():
    spans = [
        _span("gateway.request", 0.0, 10.0),
        _span("worker.execute", 1.0, 4.0),
        _span("worker.execute", 7.0, 9.0),  # resumed after migration gap
        _span("engine.decode", 1.5, 3.5),
    ]
    seg = critical_path(spans)
    # 4.0→7.0 is inside the execution hull but covered by no execute
    # span — preemption/handoff dead time, not control-plane dispatch
    assert abs(seg["suspend_resume"] - 3.0) < 1e-9
    # 0→1, 1→1.5 pre-decode execute, 3.5→4 post, 7→9 execute, 9→10
    assert abs(seg["dispatch"] - 5.0) < 1e-9
    total = sum(seg[k] for k in (
        "queue_wait", "dispatch", "prefill", "decode_device",
        "decode_host_stall", "migration", "suspend_resume"))
    assert abs(total - seg["e2e"]) < 1e-9


def test_critical_path_requires_sealed_root():
    assert critical_path([]) is None
    assert critical_path([{"name": "gateway.request", "start": 0.0,
                           "end": None}]) is None


# -- THE acceptance gate: shard SIGKILL forensics ----------------------------

TOKENS = [f"tok{i} " for i in range(30)]


async def test_shard_kill_produces_causally_ordered_incident_report():
    """SIGKILL the owning scheduler shard mid-decode with the timeline
    armed and the process clock skew-injected: one /admin/incidents read
    on a surviving member yields a causally ordered shard_lease_lost
    report with events from ≥ 3 distinct members, and every bus edge
    orders send-before-receive under HLC."""
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.gateway.app import create_app
    from gridllm_tpu.utils.config import Config

    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()

    # clock-skew injection: the process HLC's physical source jumps
    # backwards 120 s on alternating reads — stamps must stay monotone
    # and causally consistent anyway
    clock = default_clock()
    orig_now = clock.now_fn
    flip = [0]

    def skewed_now():
        flip[0] += 1
        return time.time() - (120.0 if flip[0] % 2 else 0.0)

    clock.now_fn = skewed_now

    reg = MetricsRegistry()
    pub = TimelinePublisher("obs-gw", registry=reg)
    store = TimelineStore()
    incidents = IncidentCollector(store, member="obs-gw",
                                  window_ms=10_000, registry=reg)
    pub.install()
    await pub.start(bus)
    await store.attach(bus)

    shards, gws = await make_fleet(bus)
    w = FakeWorker(bus, "w-chaos", ["m1"], stream_tokens=list(TOKENS),
                   stream_delay_s=0.02)
    await w.start()
    await bus.flush()
    await asyncio.sleep(0.2)
    jid = job_for_shard(0)

    app = create_app(bus, gws[1].registry, gws[1], Config(),
                     timeline=store, incidents=incidents)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        chunks = []

        async def on_chunk(chunk):
            chunks.append(chunk.response or "")
            if len(chunks) == 5:
                await shards[0].kill()

        result = await gws[1].submit_streaming_job(
            req(jid, stream=True), on_chunk, timeout_ms=20_000)
        assert result.success
        for _ in range(100):
            if shards[1].lease.holds(0):
                break
            await asyncio.sleep(0.05)
        assert shards[1].lease.holds(0)
        await bus.flush()

        # ONE GET on a surviving member assembles the whole story
        resp = await client.get("/admin/incidents")
        assert resp.status == 200
        body = await resp.json()
        reports = [r for r in body["incidents"]
                   if r["kind"] == "shard_lease_lost"]
        assert len(reports) == 1, body["incidents"]
        rep = reports[0]
        events = rep["events"]
        assert len(events) >= 3
        # causally ordered: the HLC sort key is non-decreasing
        keys = [stamp_key(e) for e in events]
        assert keys == sorted(keys)
        # stitched from ≥ 3 distinct members (gateway submit, surviving
        # shard's adoption, the observing member's bus edges at minimum;
        # FakeWorker is a bus stub with no flight recorder of its own)
        members = {e.get("member") for e in events if e.get("member")}
        assert len(members) >= 3, members

        # every bus edge pair orders send-before-receive despite the
        # injected 120 s skew
        timeline = await (await client.get(
            f"/admin/timeline/{jid}")).json()
        ev = timeline["events"]
        assert ev, "timeline slice empty"
        sends = [e for e in ev if e["name"] == "bus.send"]
        recvs = [e for e in ev if e["name"] == "bus.recv"]
        assert sends and recvs
        for r in recvs:
            ch = r["fields"]["channel"]
            paired = [s for s in sends
                      if s["fields"]["channel"] == ch
                      and stamp_key(s) < stamp_key(r)]
            assert paired, (ch, r)
        # the slice merges the tracer spans for the same request
        assert any(s["name"] == "gateway.request"
                   for s in timeline["spans"])

        # 404 with a typed error for unknown requests, not an empty 200
        missing = await client.get("/admin/timeline/job-never-existed")
        assert missing.status == 404
    finally:
        await client.close()
        await pub.stop()
        await store.detach()
        _cleanup_emitter()
        clock.now_fn = orig_now
        await stop_fleet(shards, gws, w)
        await bus.disconnect()


async def test_fleet_dump_aggregates_every_member_keyed_by_identity():
    """/admin/dump?fleet=1 broadcasts a collection op; every member with
    a StatusPublisher answers on the per-op reply channel, keyed by
    member identity — silent members are listed, never merged away."""
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.controlplane.status import FleetView, StatusPublisher
    from gridllm_tpu.gateway.app import create_app
    from gridllm_tpu.utils.config import Config

    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    shards, gws = await make_fleet(bus, gateways=1)
    gw = gws[0]
    view = FleetView(bus, gw.metrics, stale_after_ms=5000)
    await view.start()
    pubs = [StatusPublisher(bus, sh.scheduler, "shard", sh.member_id,
                            10_000, lease=sh.lease) for sh in shards]
    for p in pubs:
        await p.start()
    await bus.flush()
    await asyncio.sleep(0.1)

    app = create_app(bus, gw.registry, gw, Config(), fleet=view)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        plain = await (await client.get("/admin/dump")).json()
        assert "fleet" not in plain
        dump = await (await client.get("/admin/dump?fleet=1")).json()
        fleet = dump["fleet"]
        assert set(fleet["requested"]) == {"shard-0", "shard-1"}
        assert fleet["missing"] == []
        for member in ("shard-0", "shard-1"):
            art = fleet["members"][member]
            # each member's own artifact, attributed — never merged
            assert art["scheduler"]["stats"]["shard"]["member"] == member
    finally:
        await client.close()
        for p in pubs:
            await p.stop()
        await view.stop()
        await stop_fleet(shards, gws)
        await bus.disconnect()
