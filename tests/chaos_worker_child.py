"""Child process for tests/test_chaos.py: a REAL worker (tiny-llama
engine + WorkerService) over a RESP broker, to be SIGKILLed mid-job.

Usage: python chaos_worker_child.py <broker_port> <worker_id>
"""

import asyncio
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


async def main() -> None:
    broker_port, worker_id = sys.argv[1], sys.argv[2]
    from gridllm_tpu.bus import create_bus
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.utils.config import WorkerConfig
    from gridllm_tpu.worker.service import WorkerService

    eng = InferenceEngine(EngineConfig(
        model="tiny-llama", max_slots=2, page_size=8, num_pages=32,
        max_pages_per_slot=4, prefill_buckets=(16, 32),
    ))
    bus = create_bus(f"resp://127.0.0.1:{broker_port}")
    await bus.connect()
    svc = WorkerService(
        bus, {"tiny-llama": eng},
        WorkerConfig(worker_id=worker_id, heartbeat_interval_ms=150,
                     resource_monitor_interval_ms=500),
        stream_flush_ms=5,
    )
    await svc.start()
    print("CHILD_READY", flush=True)
    await asyncio.Event().wait()  # run until killed


asyncio.run(main())
