"""Test harness config.

Per SURVEY.md §4: scheduler/gateway tests run against the in-memory fake bus
and fake workers (no TPU, no model); parallelism tests run on a virtual
8-device CPU mesh. The env vars below MUST be set before jax is imported
anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
# Keep test compiles fast & deterministic
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The environment's TPU-relay plugin (sitecustomize → axon.register) forces
# jax_platforms="axon,cpu" via jax.config at interpreter startup, which makes
# the first backends() call initialize the remote TPU client — wrong (and
# hang-prone) for unit tests. Force the config back to CPU-only BEFORE any
# test imports jax. The env var alone is not enough: register() overrides it
# at the config layer.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite compiles the SAME tiny-model
# programs (prefill buckets, decode block, verify block, embed) in nearly
# every test process; on the CPU-share-constrained CI/verify box those
# repeat compiles are a large slice of the tier-1 wall clock. The cache is
# keyed by HLO hash (donation/aliasing included), so behavior is
# unchanged — and the jit TRIPWIRE (obs/perf.py) counts python-side
# signatures, not XLA compiles, so its tests are unaffected. Guarded:
# older jaxlibs without CPU cache support just skip it.
try:
    import tempfile as _tempfile

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(_tempfile.gettempdir(), "gridllm-test-xla-cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:  # noqa: BLE001 — cache is an optimization only
    pass

import asyncio  # noqa: E402

import pytest  # noqa: E402

# Lock-discipline sanitizer (ISSUE 8): GRIDLLM_SANITIZE=1 swaps the
# threading.Lock/RLock factories for instrumented proxies BEFORE any test
# module builds an engine/scheduler, so every lock those construct joins
# the lock-order graph. The session hook below fails the run on cycles.
# The shared-state sanitizer (ISSUE 13) rides the same switch: scheduler/
# registry/allocator register their hot state for cross-thread
# unguarded-write tracking, judged at session end alongside the graph.
from gridllm_tpu.analysis import lockcheck, numcheck, statecheck  # noqa: E402

if lockcheck.enabled():
    lockcheck.install()


def pytest_sessionfinish(session, exitstatus):
    if not (lockcheck.enabled() and lockcheck.installed()):
        return
    cycles = lockcheck.cycles()
    if cycles:
        lines = "\n  ".join(" -> ".join(c) for c in cycles)
        print(f"\nGRIDLLM_SANITIZE: lock-order cycle(s) observed:\n  {lines}")
        pytest.exit("lock-order cycle detected by the sanitizer",
                    returncode=3)
    edges = lockcheck.edges()
    print(f"\nGRIDLLM_SANITIZE: lock-order graph acyclic "
          f"({len(edges)} distinct edges observed)")
    state = statecheck.report()
    if not state["ok"]:
        lines = "\n  ".join(
            f"{v['object']}.{v['attr']}: {v['threads']} threads, no "
            f"common lock — " + "; ".join(v["sites"])
            for v in state["violations"])
        print(f"\nGRIDLLM_SANITIZE: cross-thread unguarded shared-state "
              f"mutation:\n  {lines}")
        pytest.exit("shared-state violation detected by the sanitizer",
                    returncode=3)
    print(f"GRIDLLM_SANITIZE: shared-state writes clean "
          f"({state['observed_attrs']} tracked attrs, "
          f"{state['tracked_objects']} live objects)")
    # numerics sanitizer (gridcheck v3): shadowed kernel dispatches must
    # stay inside the KERNELS-registry tolerances and tripwired arrays
    # finite — same exit-3 contract as the two checks above
    num = numcheck.report()
    if not num["ok"]:
        lines = "\n  ".join(
            f"{v['op']}: {v['kind']} " + (
                f"excess {v['excess']:.3e} (max err {v['max_err']:.3e}, "
                f"rtol={v['rtol']} atol={v['atol']})"
                if v["kind"] == "tolerance"
                else f"{v['bad_elements']} non-finite elements")
            for v in num["violations"])
        print(f"\nGRIDLLM_SANITIZE: kernel numerics violation(s):\n  {lines}")
        pytest.exit("numerics violation detected by the sanitizer",
                    returncode=3)
    print(f"GRIDLLM_SANITIZE: kernel numerics clean "
          f"({num['shadowed_dispatches']} shadowed dispatches, "
          f"{num['finite_checks']} finite tripwires)")


@pytest.fixture
def event_loop_policy():
    return asyncio.DefaultEventLoopPolicy()


# Minimal asyncio test support without pytest-asyncio: run `async def` tests.
def pytest_pyfunc_call(pyfuncitem):
    import inspect

    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        sig = inspect.signature(fn)
        kwargs = {k: v for k, v in pyfuncitem.funcargs.items() if k in sig.parameters}
        asyncio.run(fn(**kwargs))
        return True
    return None
