"""Scaled-control-plane tests (ISSUE 15): deterministic job→shard
mapping, lease acquire/renew/fence units, stale-shard refusal on every
mutating path, 2-gateway/2-shard in-process fleets, and the chaos
differential — kill a scheduler shard mid-decode, the surviving shard
adopts the lease (epoch bump) and replays the durable job state, and the
client stream stays byte-identical with zero orphans and zero
double-assignments (the PR 9-10 differential style, with the SCHEDULER
as the component under fire instead of the worker or the broker)."""

import asyncio
import json
import uuid

from gridllm_tpu.bus import InMemoryBus
from gridllm_tpu.controlplane.client import GatewaySubmitter
from gridllm_tpu.controlplane.lease import LEASES_KEY, ShardLeaseManager
from gridllm_tpu.controlplane.partition import ShardContext, shard_of
from gridllm_tpu.controlplane.shard import SchedulerShard, wait_for_ownership
from gridllm_tpu.controlplane.status import FleetView, StatusPublisher
from gridllm_tpu.scheduler import WorkerRegistry
from gridllm_tpu.scheduler.scheduler import (
    JobScheduler,
    shard_active_key,
    shard_queue_key,
)
from gridllm_tpu.utils.config import ControlPlaneConfig, GatewayConfig
from gridllm_tpu.utils.types import InferenceRequest, Priority, StreamChunk

from .helpers import FakeWorker, fast_config


def job_for_shard(idx: int, num_shards: int = 2) -> str:
    """A fresh job id that deterministically maps to shard ``idx``."""
    while True:
        jid = f"job-{uuid.uuid4().hex[:10]}"
        if shard_of(jid, num_shards) == idx:
            return jid


def req(job_id: str, model: str = "m1", **kw) -> InferenceRequest:
    return InferenceRequest(id=job_id, model=model, prompt="hi",
                            priority=Priority.medium, **kw)


def cp_config(shard_id: int, num_shards: int = 2,
              ttl_ms: int = 400, renew_ms: int = 80) -> ControlPlaneConfig:
    return ControlPlaneConfig(
        mode="gateway", num_shards=num_shards, shard_id=shard_id,
        lease_ttl_ms=ttl_ms, renew_interval_ms=renew_ms,
        status_interval_ms=100)


async def make_fleet(bus, num_shards: int = 2, gateways: int = 2,
                     ttl_ms: int = 400, renew_ms: int = 80):
    """An in-process 2-gateway/M-shard control plane on one bus — each
    member gets its own registry, exactly as in the per-process layout."""
    shards = []
    for i in range(num_shards):
        reg = WorkerRegistry(bus, fast_config())
        sh = SchedulerShard(
            bus, reg, fast_config(), cp_config(i, num_shards, ttl_ms,
                                               renew_ms),
            member_id=f"shard-{i}", settle_s=0.01 + 0.005 * i)
        await reg.initialize()
        await sh.start()
        shards.append(sh)
    assert await wait_for_ownership(shards, num_shards, timeout_s=5.0)
    gws = []
    for i in range(gateways):
        reg = WorkerRegistry(bus, fast_config(), observer=True)
        gw = GatewaySubmitter(bus, reg, fast_config(),
                              member_id=f"gw-{i}")
        await reg.initialize()
        await gw.initialize()
        gws.append(gw)
    return shards, gws


async def stop_fleet(shards, gws, *workers):
    for w in workers:
        await w.stop(announce=False)
    for gw in gws:
        await gw.shutdown()
        await gw.registry.shutdown()
    for sh in shards:
        await sh.stop()
        await sh.registry.shutdown()


# -- deterministic partition mapping ----------------------------------------

def test_shard_of_deterministic():
    # content-hash stability: the exact mapping is part of the protocol
    # (members of one fleet, and adoption replays, must always agree)
    assert shard_of("job-abc", 2) == shard_of("job-abc", 2)
    assert shard_of("job-abc", 1) == 0
    for jid in ("a", "job-1", "job-ffffffff", "x" * 200):
        assert 0 <= shard_of(jid, 3) < 3
    # and it is not Python's seeded hash(): a fixed pin across processes
    assert shard_of("job-pinned", 4) == 0


def test_shard_of_spreads():
    counts = [0, 0]
    for i in range(256):
        counts[shard_of(f"job-{i}", 2)] += 1
    assert min(counts) > 64  # both partitions carry real load


# -- lease acquire / renew / fence ------------------------------------------

async def test_lease_acquire_and_renew():
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    lm = ShardLeaseManager(bus, "m1", 2, home_shards=(0,),
                           ttl_ms=400, renew_ms=60, settle_s=0.01)
    await lm.start()
    assert lm.holds(0) and lm.fenced(0)
    rec = json.loads(await bus.hget(LEASES_KEY, "0"))
    assert rec["owner"] == "m1" and rec["epoch"] == 1
    # the sweep adopts the unowned second partition
    await asyncio.sleep(0.3)
    assert lm.holds(1)
    await lm.stop()
    assert await bus.hget(LEASES_KEY, "0") is None  # released
    await bus.disconnect()


async def test_lease_adoption_bumps_epoch_and_deposes():
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    lost: list[tuple[int, str]] = []
    a = ShardLeaseManager(bus, "a", 1, home_shards=(0,), ttl_ms=300,
                          renew_ms=50, settle_s=0.01,
                          on_lost=lambda i, r: lost.append((i, r)))
    await a.start()
    assert a.epochs() == {"0": 1}
    # SIGKILL-style: a stops renewing but never releases
    a.kill()
    b = ShardLeaseManager(bus, "b", 1, home_shards=(), ttl_ms=300,
                          renew_ms=50, settle_s=0.01)
    await b.start()
    await asyncio.sleep(0.6)  # a's record ages past the TTL; b adopts
    assert b.holds(0) and b.epochs() == {"0": 2}
    # a resurrects: its next renewal sees the foreign epoch and deposes
    await a._renew(0)
    assert not a.holds(0) and lost == [(0, "deposed")]
    assert not a.fenced(0)
    await b.stop()
    await bus.disconnect()


async def test_lease_self_fences_without_renewals():
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    lm = ShardLeaseManager(bus, "m1", 1, home_shards=(0,), ttl_ms=150,
                           renew_ms=50, settle_s=0.01)
    assert await lm.try_acquire(0, adopted=False)  # no loop started
    assert lm.fenced(0)
    await asyncio.sleep(0.2)
    # renewals never ran: the member cannot prove ownership → fenced out
    assert not lm.fenced(0) and lm.holds(0)
    await lm.stop()
    await bus.disconnect()


# -- stale shard refused on every mutating path -----------------------------

class _DeadLease:
    """A lease view that answers 'held but stale' — the deposed-shard
    limbo between losing the lease and noticing."""

    def __init__(self, num_shards=1):
        self.num = num_shards

    def held_shards(self):
        return list(range(self.num))

    def held_epochs(self):
        return {i: 1 for i in range(self.num)}

    def holds(self, idx):
        return True

    def fenced(self, idx):
        return False

    def epochs(self):
        return {str(i): 1 for i in range(self.num)}


async def test_stale_shard_refuses_every_mutating_path():
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    reg = WorkerRegistry(bus, fast_config())
    ctx = ShardContext(1, "stale", _DeadLease())
    sched = JobScheduler(bus, reg, fast_config(), shard=ctx)
    await reg.initialize()
    await sched.initialize()
    w = FakeWorker(bus, "w1", ["m1"])
    await w.start()
    await bus.flush()

    jid = job_for_shard(0, 1)
    await sched.add_job(req(jid))
    await bus.flush()
    await asyncio.sleep(0.2)
    # queued but never assigned: the fence refused the dispatch
    assert sched.active_jobs == {} and len(sched.job_queue) == 1
    assert w.assignments == []
    fenced = sched._shard_fenced
    assert fenced.value(op="assign") >= 1

    # timeout / orphan / cancel / failure paths all refuse too
    from gridllm_tpu.utils.types import JobAssignment, JobResult

    assignment = JobAssignment(jobId=jid, workerId="w1",
                               request=req(jid), timeout=5000)
    sched.active_jobs[jid] = assignment
    await sched._handle_job_timeout(jid)
    assert jid in sched.active_jobs  # refused, not claimed
    assert fenced.value(op="timeout") == 1
    await sched._orphan_job(assignment, reason="test")
    assert fenced.value(op="orphan") == 1
    assert not await sched.cancel_job(jid)
    assert fenced.value(op="cancel") == 1
    fail = JobResult(jobId=jid, workerId="w1", success=False,
                     error="boom", retryable=True)
    await sched._on_job_failed("job:failed", fail.model_dump_json())
    assert fenced.value(op="failure") == 1
    assert jid in sched.active_jobs  # the failure path never touched it

    sched.active_jobs.pop(jid, None)
    await w.stop(announce=False)
    await sched.shutdown()
    await reg.shutdown()
    await bus.disconnect()


# -- fleet routing / remote submit ------------------------------------------

async def test_fleet_submits_route_to_owning_shard():
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    shards, gws = await make_fleet(bus)
    w = FakeWorker(bus, "w1", ["m1"], max_concurrent=8)
    await w.start()
    await bus.flush()
    await asyncio.sleep(0.2)  # registries ingest the registration

    jobs = [job_for_shard(0), job_for_shard(0),
            job_for_shard(1), job_for_shard(1)]
    results = await asyncio.gather(*[
        gws[i % 2].submit_and_wait(req(jid), timeout_ms=5000)
        for i, jid in enumerate(jobs)])
    assert all(r.success for r in results)
    # exactly-once execution, and each shard dispatched ITS partition
    assert sorted(w.processed) == sorted(jobs)
    assert len(w.assignments) == 4
    for sh, own_jobs in ((shards[0], jobs[:2]), (shards[1], jobs[2:])):
        st = sh.scheduler.get_stats()
        assert st["totalJobsProcessed"] == 2
        assert st["shard"]["role"] == "shard"
        accepted = sh.scheduler._ctrl_submits.value(event="accepted")
        parked = sh.scheduler._ctrl_submits.value(event="parked")
        # non-owned submits are PARKED (durable queue record for the
        # partition's owner/adopter), never silently ignored
        assert accepted == 2 and parked == 2
        del own_jobs
    await stop_fleet(shards, gws, w)
    await bus.disconnect()


async def test_remote_cancel_reaches_owning_shard():
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    shards, gws = await make_fleet(bus)
    w = FakeWorker(bus, "w1", ["m1"], delay_s=2.0)
    await w.start()
    await bus.flush()
    await asyncio.sleep(0.2)

    jid = job_for_shard(1)
    task = asyncio.create_task(
        gws[0].submit_and_wait(req(jid), timeout_ms=4000))
    await asyncio.sleep(0.3)  # let it dispatch
    assert jid in shards[1].scheduler.active_jobs
    await gws[0].cancel_job(jid, reason="client_disconnect")
    await bus.flush()
    await asyncio.sleep(0.1)
    assert jid not in shards[1].scheduler.active_jobs
    assert w.cancelled == [jid]
    task.cancel()
    try:
        await task
    except (asyncio.CancelledError, Exception):  # noqa: BLE001
        pass
    await stop_fleet(shards, gws, w)
    await bus.disconnect()


# -- chaos differential: kill a scheduler shard mid-decode -------------------

TOKENS = [f"tok{i} " for i in range(40)]


async def _stream_run(gw, jid: str, kill_cb=None, kill_after_chunks=0):
    chunks: list[str] = []

    async def on_chunk(chunk: StreamChunk) -> None:
        chunks.append(chunk.response or "")
        if kill_cb is not None and len(chunks) == kill_after_chunks:
            await kill_cb()

    result = await gw.submit_streaming_job(req(jid, stream=True),
                                           on_chunk, timeout_ms=20000)
    return result, "".join(chunks)


async def test_kill_shard_mid_decode_stream_byte_identical():
    """THE acceptance gate: SIGKILL-style death of the owning scheduler
    shard mid-decode with 2 gateways live. The surviving shard adopts the
    lease (epoch 2) and replays the durable assignment; the worker and
    the gateway never notice; the client stream is byte-identical to the
    undisturbed run with zero orphans and zero double-assignments."""
    # baseline: undisturbed fleet
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    shards, gws = await make_fleet(bus)
    w = FakeWorker(bus, "w-base", ["m1"], stream_tokens=list(TOKENS),
                   stream_delay_s=0.02)
    await w.start()
    await bus.flush()
    await asyncio.sleep(0.2)
    jid = job_for_shard(0)
    result, baseline = await _stream_run(gws[0], jid)
    assert result.success and baseline == "".join(TOKENS)
    await stop_fleet(shards, gws, w)
    await bus.disconnect()

    # chaos: same fleet shape, owning shard killed mid-stream
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    shards, gws = await make_fleet(bus)
    w = FakeWorker(bus, "w-chaos", ["m1"], stream_tokens=list(TOKENS),
                   stream_delay_s=0.02)
    await w.start()
    await bus.flush()
    await asyncio.sleep(0.2)
    jid = job_for_shard(0)

    async def kill_owner() -> None:
        await shards[0].kill()

    result, streamed = await _stream_run(gws[1], jid, kill_cb=kill_owner,
                                         kill_after_chunks=5)
    assert result.success
    assert streamed == baseline  # byte-identical through the shard death

    # the survivor adopted the partition with an epoch bump...
    for _ in range(100):
        if shards[1].lease.holds(0):
            break
        await asyncio.sleep(0.05)
    assert shards[1].lease.holds(0)
    assert shards[1].lease.epochs()["0"] == 2
    # ... zero orphans, zero double-assignments, no duplicate work
    assert len(w.assignments) == 1 and w.processed == [jid]
    for sh in shards:
        jt = sh.scheduler._jobs_total
        assert jt.value(event="orphaned") == 0

    # the control plane is fully live again: a second request on the
    # adopted partition is served end to end through the OTHER gateway
    jid2 = job_for_shard(0)
    result2, streamed2 = await _stream_run(gws[0], jid2)
    assert result2.success and streamed2 == "".join(TOKENS)
    assert shards[1].scheduler.get_stats()["shard"]["shards"] == [0, 1]
    await stop_fleet(shards, gws, w)
    await bus.disconnect()


async def test_adoption_replays_queued_jobs_from_bus():
    """A job still QUEUED when its shard dies is replayed from the
    durable queue record and dispatched by the adopter."""
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    shards, gws = await make_fleet(bus)
    # no worker yet: the job stays queued on its owning shard
    jid = job_for_shard(0)
    task = asyncio.create_task(
        gws[0].submit_and_wait(req(jid), timeout_ms=15000))
    await bus.flush()
    await asyncio.sleep(0.2)
    assert len(shards[0].scheduler.job_queue) == 1
    assert await bus.hget(shard_queue_key(0), jid) is not None
    await shards[0].kill()
    # a worker arrives while the partition is orphaned; the adopter
    # replays the queued record and dispatches
    w = FakeWorker(bus, "w1", ["m1"])
    await w.start()
    result = await task
    assert result.success
    assert w.processed == [jid]
    assert shards[1].lease.holds(0)
    assert await bus.hget(shard_queue_key(0), jid) is None
    await stop_fleet(shards, gws, w)
    await bus.disconnect()


async def test_adoption_drops_already_resolved_active_record():
    """A job that COMPLETES while its partition is owner-less must not be
    resurrected as a live assignment at adoption (the _recent_done
    buffer) — its durable active record is stale, not a live job."""
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    shards, gws = await make_fleet(bus)
    w = FakeWorker(bus, "w1", ["m1"], stream_tokens=list(TOKENS),
                   stream_delay_s=0.02)
    await w.start()
    await bus.flush()
    await asyncio.sleep(0.2)
    jid = job_for_shard(0)

    async def kill_owner() -> None:
        await shards[0].kill()

    # kill LATE in the stream: the job completes before adoption lands
    result, streamed = await _stream_run(gws[0], jid, kill_cb=kill_owner,
                                         kill_after_chunks=36)
    assert result.success and streamed == "".join(TOKENS)
    for _ in range(100):
        if shards[1].lease.holds(0):
            break
        await asyncio.sleep(0.05)
    await asyncio.sleep(0.2)
    # the adopter holds no ghost of the finished job
    assert jid not in shards[1].scheduler.active_jobs
    assert await bus.hget(shard_active_key(0), jid) is None
    assert len(w.assignments) == 1
    await stop_fleet(shards, gws, w)
    await bus.disconnect()


# -- aggregation view --------------------------------------------------------

async def test_submit_during_ownerless_window_is_parked_and_recovered():
    """A job submitted BETWEEN a shard's death and its lease expiring
    (the window where the dead owner still looks alive) must not be
    lost: the surviving non-owner parks it into the partition's durable
    queue record and executes it after adopting the lease."""
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    # a generous TTL: the submit must land INSIDE the owner-less window
    # even under the sanitizer's instrumentation slowdown
    shards, gws = await make_fleet(bus, ttl_ms=1500, renew_ms=100)
    w = FakeWorker(bus, "w1", ["m1"])
    await w.start()
    await bus.flush()
    await asyncio.sleep(0.2)

    await shards[0].kill()  # lease record still live for ~1.5 s
    jid = job_for_shard(0)
    task = asyncio.create_task(
        gws[0].submit_and_wait(req(jid), timeout_ms=15000))
    await bus.flush()
    # nobody owns the partition yet: the job lives ONLY as the parked
    # durable record written by the surviving non-owner
    assert jid not in [q.request.id for q in shards[1].scheduler.job_queue]
    assert await bus.hget(shard_queue_key(0), jid) is not None
    assert shards[1].scheduler._ctrl_submits.value(event="parked") >= 1
    result = await task  # adopter replays the parked record
    assert result.success and w.processed == [jid]
    assert shards[1].lease.holds(0)
    await stop_fleet(shards, gws, w)
    await bus.disconnect()


async def test_owner_reconciles_parked_record_it_never_saw():
    """The owner's sweep adopts durable queued records it has no local
    copy of (a park from a missed ctrl:submit delivery) — and collects
    ghosts of already-resolved jobs instead of re-executing them."""
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    shards, gws = await make_fleet(bus)
    w = FakeWorker(bus, "w1", ["m1"])
    await w.start()
    await bus.flush()
    await asyncio.sleep(0.2)

    # simulate a parked submit the owner never received on ctrl:submit:
    # write ONLY the durable record (what a non-owner's park leaves) and
    # await the per-job result channel like a gateway waiter would
    import json as _json

    from gridllm_tpu.utils.types import JobResult

    jid = job_for_shard(0)
    fut: asyncio.Future = asyncio.get_running_loop().create_future()

    async def on_result(_ch: str, raw: str) -> None:
        if not fut.done():
            fut.set_result(JobResult.model_validate_json(raw))

    sub = await bus.subscribe(f"job:result:{jid}", on_result)
    await bus.hset(shard_queue_key(0), jid, _json.dumps({
        "seq": 10_000, "request": req(jid).model_dump(mode="json")}))
    result = await asyncio.wait_for(fut, 15)  # ~500 ms reconcile tick
    await sub.unsubscribe()
    assert result.success and jid in w.processed
    assert shards[0].scheduler._ctrl_submits.value(event="reconciled") == 1

    # ghost of a resolved job: reconcile must collect, never re-execute
    ghost = _json.dumps({"seq": 10_001,
                         "request": req(jid).model_dump(mode="json")})
    await bus.hset(shard_queue_key(0), jid, ghost)
    await asyncio.sleep(0.8)
    assert await bus.hget(shard_queue_key(0), jid) is None
    assert w.processed.count(jid) == 1
    await stop_fleet(shards, gws, w)
    await bus.disconnect()


async def test_observer_registry_prunes_silently_dead_worker():
    """Gateway replicas hold no death verdicts, but their LOCAL worker
    view must still age out a SIGKILLed worker (nothing broadcasts the
    shards' removals) — /health/workers is documented as fleet-wide."""
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    reg = WorkerRegistry(bus, fast_config(), observer=True)
    await reg.initialize()
    w = FakeWorker(bus, "w1", ["m1"], heartbeat_interval_s=0.1)
    await w.start()
    await bus.flush()
    assert reg.get_worker("w1") is not None
    await w.die()  # no unregister/disconnect announcement
    await asyncio.sleep(1.2)  # heartbeat timeout 600 ms + prune tick
    assert reg.get_worker("w1") is None
    # the bus hash is untouched — removal authority stays with shards
    assert await bus.hget("workers", "w1") is not None
    await reg.shutdown()
    await bus.disconnect()


async def test_fleet_view_aggregates_per_member():
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    shards, gws = await make_fleet(bus)
    view = FleetView(bus, gws[0].metrics, stale_after_ms=1000)
    await view.start()
    pubs = [StatusPublisher(bus, sh.scheduler, "shard", sh.member_id,
                            100, lease=sh.lease) for sh in shards]
    pubs.append(StatusPublisher(bus, gws[0], "gateway",
                                gws[0].member_id, 100))
    for p in pubs:
        await p.publish_once()
    await bus.flush()

    members = view.members()
    assert set(members) == {"shard-0", "shard-1", gws[0].member_id}
    assert members["shard-0"]["role"] == "shard"
    merged = view.merged_stats()
    assert merged["numShards"] == 2
    # per-member stats keep their shard identity — nothing summed blind
    assert merged["perMember"]["shard-1"]["shard"]["member"] == "shard-1"
    slo = view.merged_slo()
    assert set(slo) == set(members)
    # collector exports per-shard gauges on the gateway registry
    view._collect()
    held = {s: view._held_gauge.value(shard=s) for s in ("0", "1")}
    assert held == {"0": 1, "1": 1}
    await view.stop()
    await stop_fleet(shards, gws)
    await bus.disconnect()


async def test_fleet_view_flags_lost_lease():
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    shards, gws = await make_fleet(bus)
    view = FleetView(bus, gws[0].metrics, stale_after_ms=300)
    await view.start()
    p0 = StatusPublisher(bus, shards[0].scheduler, "shard",
                         shards[0].member_id, 100, lease=shards[0].lease)
    p1 = StatusPublisher(bus, shards[1].scheduler, "shard",
                         shards[1].member_id, 100, lease=shards[1].lease)
    await p0.publish_once()
    await p1.publish_once()
    await bus.flush()
    view._collect()
    assert view._held_gauge.value(shard="0") == 1
    # shard 0 dies; its envelope goes stale; only shard 1 keeps publishing
    await shards[0].kill()
    await asyncio.sleep(0.4)
    await p1.publish_once()
    await bus.flush()
    view._collect()
    assert view._held_gauge.value(shard="0") == 0  # lease-lost → alert
    assert view._held_gauge.value(shard="1") == 1
    await view.stop()
    await stop_fleet(shards, gws)
    await bus.disconnect()


# -- satellites --------------------------------------------------------------

async def test_ratelimit_fleet_scope_shares_buckets():
    """Two middleware instances (two replicas) over one bus: the fleet
    scope counts BOTH replicas' requests against one bucket; the replica
    scope keeps the documented per-process semantics."""
    from aiohttp import web
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.gateway.ratelimit import rate_limit_middleware
    from gridllm_tpu.obs import MetricsRegistry

    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()

    async def make_app(scope: str, metrics):
        cfg = GatewayConfig(rate_limit_window_ms=60_000,
                            rate_limit_max_requests=4,
                            rate_limit_scope=scope)
        app = web.Application(
            middlewares=[rate_limit_middleware(cfg, bus=bus,
                                               metrics=metrics)])

        async def ok(_r):
            return web.json_response({"ok": True})

        app.add_routes([web.get("/t", ok)])
        client = TestClient(TestServer(app))
        await client.start_server()
        return client

    metrics = MetricsRegistry()
    c1 = await make_app("fleet", metrics)
    c2 = await make_app("fleet", metrics)
    statuses = []
    for i in range(6):
        client = (c1, c2)[i % 2]
        resp = await client.get("/t")
        statuses.append(resp.status)
    # 4 allowed FLEET-WIDE, the rest throttled regardless of replica
    assert statuses.count(200) == 4 and statuses.count(429) == 2
    rej = metrics.counter(
        "gridllm_ratelimit_rejections_total",
        "Requests throttled with HTTP 429, by bucket scope (replica "
        "= per-process buckets, so N gateway replicas multiply the "
        "configured limit by N; fleet = bus-shared buckets).",
        ("scope",))
    assert rej.value(scope="fleet") == 2
    await c1.close()
    await c2.close()

    # replica scope: each process gets its own budget (documented N×)
    m2 = MetricsRegistry()
    r1 = await make_app("replica", m2)
    r2 = await make_app("replica", m2)
    statuses = []
    for i in range(8):
        client = (r1, r2)[i % 2]
        resp = await client.get("/t")
        statuses.append(resp.status)
    assert statuses.count(200) == 8  # 4 per replica — none throttled
    await r1.close()
    await r2.close()
    await bus.disconnect()


async def test_gateway_replica_http_surface_end_to_end():
    """The full replica wiring (create_app over a GatewaySubmitter +
    FleetView): a real HTTP generate served through the shards, and the
    fleet-wide /admin/slo + /health/workers views from the replica."""
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.gateway.app import create_app
    from gridllm_tpu.utils.config import Config

    bus = InMemoryBus(key_prefix="GridLLM:")
    await bus.connect()
    shards, gws = await make_fleet(bus, gateways=1)
    gw = gws[0]
    view = FleetView(bus, gw.metrics, stale_after_ms=2000)
    await view.start()
    pubs = [StatusPublisher(bus, sh.scheduler, "shard", sh.member_id,
                            100, lease=sh.lease) for sh in shards]
    w = FakeWorker(bus, "w1", ["m1"])
    await w.start()
    await bus.flush()
    await asyncio.sleep(0.2)

    app = create_app(bus, gw.registry, gw, Config(), fleet=view)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        resp = await client.post("/ollama/api/generate", json={
            "model": "m1", "prompt": "hello", "stream": False})
        assert resp.status == 200
        body = await resp.json()
        assert body["response"] == "canned response"
        assert len(w.processed) == 1

        for p in pubs:
            await p.publish_once()
        await bus.flush()
        slo = await (await client.get("/admin/slo")).json()
        assert slo["shard"]["role"] == "gateway"
        assert set(slo["fleet"]) == {"shard-0", "shard-1"}
        workers = await (await client.get("/health/workers")).json()
        cp = workers["controlPlane"]
        assert cp["numShards"] == 2
        assert set(cp["members"]) == {"shard-0", "shard-1"}
        dump = await (await client.get("/admin/dump")).json()
        assert set(dump["controlPlane"]["members"]) == {"shard-0",
                                                        "shard-1"}
        metrics_text = await (await client.get("/metrics")).text()
        assert "gridllm_shard_lease_held" in metrics_text
        assert "gridllm_ctrl_submits_total" in metrics_text
    finally:
        await client.close()
        await view.stop()
        await stop_fleet(shards, gws, w)
        await bus.disconnect()


async def test_stats_carry_shard_identity_in_local_mode():
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    reg = WorkerRegistry(bus, fast_config())
    sched = JobScheduler(bus, reg, fast_config())
    await reg.initialize()
    await sched.initialize()
    st = sched.get_stats()
    assert st["shard"] == {"role": "local", "member": "local",
                           "shards": [0], "numShards": 1}
    await sched.shutdown()
    await reg.shutdown()
    await bus.disconnect()
