"""Model numerics goldens (SURVEY.md §4 rebuild plan: "numerical golden
tests for the new JAX engine (logits vs HF reference per layer)").

Two layers of oracle:
1. `forward` vs transformers' torch implementation on an identical tiny
   config + identical weights (fp32, CPU) — catches convention drift
   (rope pairing, norm placement, GQA grouping, weight transposes).
2. `prefill`+`decode_step` vs `forward` — the paged-cache path must
   reproduce the cache-free path token-for-token.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from gridllm_tpu.models import llama
from gridllm_tpu.models.configs import get_config
from gridllm_tpu.ops.kvcache import PagedKVCache, PageAllocator

CFG = get_config("tiny-llama")


@pytest.fixture(scope="module")
def params_fp32():
    return llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _hf_model(params):
    torch = pytest.importorskip("torch")
    pytest.importorskip("transformers")
    from transformers import LlamaForCausalLM

    model = LlamaForCausalLM(CFG.hf_config()).eval()
    sd = {}

    def put(name, arr, transpose):
        a = np.asarray(arr, np.float32)
        sd[name] = torch.from_numpy(a.T.copy() if transpose else a.copy())

    put("model.embed_tokens.weight", params["embed"], False)
    lp = params["layers"]
    for i in range(CFG.num_layers):
        pre = f"model.layers.{i}."
        put(pre + "input_layernorm.weight", lp["attn_norm"][i], False)
        put(pre + "self_attn.q_proj.weight", lp["wq"][i], True)
        put(pre + "self_attn.k_proj.weight", lp["wk"][i], True)
        put(pre + "self_attn.v_proj.weight", lp["wv"][i], True)
        put(pre + "self_attn.o_proj.weight", lp["wo"][i], True)
        put(pre + "post_attention_layernorm.weight", lp["mlp_norm"][i], False)
        put(pre + "mlp.gate_proj.weight", lp["w_gate"][i], True)
        put(pre + "mlp.up_proj.weight", lp["w_up"][i], True)
        put(pre + "mlp.down_proj.weight", lp["w_down"][i], True)
    put("model.norm.weight", params["final_norm"], False)
    put("lm_head.weight", params["lm_head"], True)
    model.load_state_dict(sd)
    return model, torch


def test_forward_matches_hf(params_fp32):
    model, torch = _hf_model(params_fp32)
    tokens = np.array([[5, 17, 99, 3, 42, 7, 250, 1]], np.int32)
    ours = np.asarray(llama.forward(params_fp32, CFG, jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = model(torch.from_numpy(tokens).long()).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_convert_hf_state_dict_roundtrip(params_fp32):
    """convert_hf_state_dict(hf_model.state_dict()) reproduces our params."""
    model, _torch = _hf_model(params_fp32)
    back = llama.convert_hf_state_dict(CFG, model.state_dict(), dtype=jnp.float32)
    tokens = jnp.asarray([[9, 8, 7, 6, 5]], jnp.int32)
    a = llama.forward(params_fp32, CFG, tokens)
    b = llama.forward(back, CFG, tokens)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def _make_cache(dtype=jnp.float32, page_size=8, num_pages=16, slots=4, maxp=8):
    cache = PagedKVCache.create(
        CFG.num_layers, num_pages, page_size, CFG.num_kv_heads, CFG.head_dim_,
        slots, maxp, dtype=dtype,
    )
    alloc = PageAllocator(num_pages, page_size, maxp)
    return cache, alloc


def test_prefill_decode_match_forward(params_fp32):
    """Greedy continuation via prefill+decode == argmax chain of `forward`."""
    prompt = [5, 17, 99, 3, 42]
    n_gen = 6
    # Oracle: repeatedly run the cache-free forward on the growing sequence.
    seq = list(prompt)
    oracle = []
    for _ in range(n_gen):
        logits = llama.forward(params_fp32, CFG, jnp.asarray([seq], jnp.int32))
        nxt = int(jnp.argmax(logits[0, -1]))
        oracle.append(nxt)
        seq.append(nxt)

    # Paged path: prefill slot 2 (arbitrary), then decode step by step.
    cache, alloc = _make_cache()
    slot = 2
    bucket = 8  # padded prompt bucket
    total = len(prompt) + n_gen
    alloc.alloc(slot, total)
    row = jnp.asarray(alloc.table_row(slot), jnp.int32)
    padded = jnp.asarray(prompt + [0] * (bucket - len(prompt)), jnp.int32)
    logits, cache = llama.prefill(
        params_fp32, CFG, padded, jnp.int32(len(prompt)), cache,
        jnp.int32(slot), row,
    )
    got = [int(jnp.argmax(logits))]
    tokens = jnp.zeros((cache.max_slots,), jnp.int32).at[slot].set(got[0])
    active = jnp.zeros((cache.max_slots,), bool).at[slot].set(True)
    for _ in range(n_gen - 1):
        logits, cache = llama.decode_step(params_fp32, CFG, tokens, cache, active)
        nxt = int(jnp.argmax(logits[slot]))
        got.append(nxt)
        tokens = tokens.at[slot].set(nxt)
    assert got == oracle


def test_decode_inactive_slots_untouched(params_fp32):
    """Inactive slots must not advance lengths or corrupt the pool."""
    cache, alloc = _make_cache()
    alloc.alloc(1, 8)
    row = jnp.asarray(alloc.table_row(1), jnp.int32)
    padded = jnp.asarray([5, 6, 7, 0, 0, 0, 0, 0], jnp.int32)
    _, cache = llama.prefill(
        params_fp32, CFG, padded, jnp.int32(3), cache, jnp.int32(1), row
    )
    lengths_before = np.asarray(cache.lengths)
    tokens = jnp.zeros((cache.max_slots,), jnp.int32)
    active = jnp.zeros((cache.max_slots,), bool)  # nobody active
    _, cache2 = llama.decode_step(params_fp32, CFG, tokens, cache, active)
    np.testing.assert_array_equal(np.asarray(cache2.lengths), lengths_before)
    np.testing.assert_allclose(np.asarray(cache2.k), np.asarray(cache.k))


def test_two_slot_isolation(params_fp32):
    """Two concurrent slots produce the same tokens as each alone (continuous
    batching must not cross-contaminate)."""
    prompts = {0: [5, 17, 99], 3: [250, 1, 2, 3, 4]}
    outs = {}
    for mode in ("together", "alone0", "alone3"):
        cache, alloc = _make_cache()
        slots = (
            list(prompts) if mode == "together"
            else [0] if mode == "alone0" else [3]
        )
        tokens = jnp.zeros((cache.max_slots,), jnp.int32)
        active = jnp.zeros((cache.max_slots,), bool)
        for s in slots:
            p = prompts[s]
            alloc.alloc(s, len(p) + 4)
            row = jnp.asarray(alloc.table_row(s), jnp.int32)
            padded = jnp.asarray(p + [0] * (8 - len(p)), jnp.int32)
            logits, cache = llama.prefill(
                params_fp32, CFG, padded, jnp.int32(len(p)), cache,
                jnp.int32(s), row,
            )
            tokens = tokens.at[s].set(int(jnp.argmax(logits)))
            active = active.at[s].set(True)
        gen = {s: [int(tokens[s])] for s in slots}
        for _ in range(3):
            logits, cache = llama.decode_step(params_fp32, CFG, tokens, cache, active)
            for s in slots:
                nxt = int(jnp.argmax(logits[s]))
                gen[s].append(nxt)
                tokens = tokens.at[s].set(nxt)
        outs[mode] = gen
    assert outs["together"][0] == outs["alone0"][0]
    assert outs["together"][3] == outs["alone3"][3]


def test_mistral_sliding_window_matches_hf():
    """Uniform sliding-window llama skeleton (mistral v0.1 class) vs HF
    MistralForCausalLM, sequence longer than the window so the mask
    actually truncates; plus prefill+decode chain parity."""
    import numpy as np
    import pytest
    import torch
    import transformers

    from gridllm_tpu.models import llama
    from gridllm_tpu.models.configs import get_config
    from gridllm_tpu.ops.kvcache import PagedKVCache, PageAllocator

    cfg = get_config("tiny-mistral")
    assert cfg.sliding_window == 8
    hf_cfg = cfg.hf_config()
    assert hf_cfg.model_type == "mistral"
    assert hf_cfg.sliding_window == 8
    torch.manual_seed(0)
    with torch.no_grad():
        model = transformers.MistralForCausalLM(hf_cfg).eval()
    params = llama.convert_hf_state_dict(
        cfg, model.state_dict(), jnp.float32)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 24))
    ours = np.asarray(llama.forward(params, cfg, jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = model(
            input_ids=torch.from_numpy(tokens.astype(np.int64))
        ).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    # paged prefill + decode chain must agree with forward past the window
    prompt = [int(t) for t in tokens[0][:12]]
    cache = PagedKVCache.create(
        cfg.num_layers, num_pages=16, page_size=8,
        num_kv_heads=cfg.num_kv_heads, head_dim=cfg.head_dim_,
        max_slots=2, max_pages_per_slot=8, dtype=jnp.float32)
    alloc = PageAllocator(16, 8, 8)
    alloc.alloc(0, 32)
    row = jnp.asarray(alloc.table_row(0), jnp.int32)
    logits, cache = llama.prefill(
        params, cfg, jnp.asarray(prompt, jnp.int32), jnp.int32(len(prompt)),
        cache, jnp.int32(0), row)
    seq = list(prompt)
    for _ in range(3):
        ref = np.asarray(llama.forward(
            params, cfg, jnp.asarray([seq], jnp.int32)))[0, -1]
        np.testing.assert_allclose(np.asarray(logits), ref, rtol=2e-4, atol=2e-4)
        nxt = int(np.argmax(ref))
        seq.append(nxt)
        tok = jnp.zeros((2,), jnp.int32).at[0].set(nxt)
        active = jnp.zeros((2,), bool).at[0].set(True)
        dec, cache = llama.decode_step(params, cfg, tok, cache, active)
        logits = dec[0]
