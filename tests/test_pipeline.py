"""Pipeline parallelism (parallel/pipeline.py): numerical parity with the
single-device engine ops on the virtual 8-device CPU mesh, plus the
engine serving end-to-end over a pp×dp×tp mesh (SURVEY.md §2.5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gridllm_tpu.models import llama
from gridllm_tpu.models.configs import get_config
from gridllm_tpu.ops.kvcache import PagedKVCache, PageAllocator
from gridllm_tpu.parallel import pipeline
from gridllm_tpu.parallel.mesh import MeshConfig, build_mesh
from gridllm_tpu.parallel.sharding import shard_cache, shard_params

CFG = get_config("tiny-llama")  # num_layers=2 → 1 layer per stage at pp=2


def _fresh_cache(dtype=jnp.float32):
    return PagedKVCache.create(
        CFG.num_layers, num_pages=16, page_size=8,
        num_kv_heads=CFG.num_kv_heads, head_dim=CFG.head_dim_,
        max_slots=4, max_pages_per_slot=4, dtype=dtype,
    )


def _alloc_row():
    alloc = PageAllocator(16, 8, 4)
    alloc.alloc(0, 16)
    return jnp.asarray(alloc.table_row(0), jnp.int32)


@pytest.fixture(scope="module")
def pp_mesh():
    return build_mesh(MeshConfig(pp=2, dp=2, tp=2))


def test_pp_prefill_decode_match_single_device(pp_mesh):
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = jnp.asarray([5, 7, 11, 13, 17, 19, 23, 29], jnp.int32)
    row = _alloc_row()

    ref_logits, ref_cache = llama.prefill(
        params, CFG, prompt, jnp.int32(8), _fresh_cache(), jnp.int32(0), row)
    tok = jnp.zeros((4,), jnp.int32).at[0].set(3)
    active = jnp.zeros((4,), bool).at[0].set(True)
    ref_dec, ref_cache2 = llama.decode_step(params, CFG, tok, ref_cache, active)

    sp_params = shard_params(params, pp_mesh)
    sp_cache = shard_cache(_fresh_cache(), pp_mesh)
    pp_logits, pp_cache = pipeline.prefill(
        sp_params, CFG, prompt, jnp.int32(8), sp_cache, jnp.int32(0), row,
        mesh=pp_mesh)
    np.testing.assert_allclose(
        np.asarray(pp_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(pp_cache.k), np.asarray(ref_cache.k), rtol=2e-4, atol=2e-4)
    assert int(pp_cache.lengths[0]) == 8

    pp_dec, pp_cache2 = pipeline.decode_step(
        sp_params, CFG, tok, pp_cache, active, mesh=pp_mesh)
    np.testing.assert_allclose(
        np.asarray(pp_dec), np.asarray(ref_dec), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(pp_cache2.k), np.asarray(ref_cache2.k), rtol=2e-4, atol=2e-4)
    assert int(pp_cache2.lengths[0]) == 9


def test_pp_prefill_chunk_matches_single_device(pp_mesh):
    params = llama.init_params(CFG, jax.random.PRNGKey(1), dtype=jnp.float32)
    row = _alloc_row()
    ids = jnp.asarray(list(range(2, 18)), jnp.int32)  # 16 tokens, 2 chunks of 8

    ref_cache = _fresh_cache()
    for s0 in (0, 8):
        ref_logits, ref_cache = llama.prefill_chunk(
            params, CFG, ids[s0:s0 + 8], jnp.int32(s0), jnp.int32(8),
            ref_cache, jnp.int32(0), row)

    sp_params = shard_params(params, pp_mesh)
    pp_cache = shard_cache(_fresh_cache(), pp_mesh)
    for s0 in (0, 8):
        pp_logits, pp_cache = pipeline.prefill_chunk(
            sp_params, CFG, ids[s0:s0 + 8], jnp.int32(s0), jnp.int32(8),
            pp_cache, jnp.int32(0), row, mesh=pp_mesh)
    np.testing.assert_allclose(
        np.asarray(pp_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(pp_cache.k), np.asarray(ref_cache.k), rtol=2e-4, atol=2e-4)
    assert int(pp_cache.lengths[0]) == 16


def test_pp_validate_rejects_bad_shapes():
    mesh3 = build_mesh(MeshConfig(pp=4, tp=2))  # L=2 % pp=4 != 0
    with pytest.raises(ValueError, match="not divisible"):
        pipeline.validate(CFG, mesh3)
    sp_mesh = build_mesh(MeshConfig(pp=2, sp=2, tp=2))
    with pytest.raises(ValueError, match="sp"):
        pipeline.validate(CFG, sp_mesh)
    mix = get_config("tiny-mixtral")
    with pytest.raises(ValueError, match="llama-skeleton"):
        pipeline.validate(mix, build_mesh(MeshConfig(pp=2, tp=2, dp=2)))


def test_engine_serves_over_pp_mesh():
    """End-to-end: engine with a pp×dp×tp mesh produces the same tokens as
    the unmeshed engine (temperature 0, fixed seed)."""
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.engine.engine import GenerationRequest

    def run(mesh_cfg):
        eng = InferenceEngine(EngineConfig(
            model="tiny-llama", max_slots=2, page_size=8, num_pages=32,
            max_pages_per_slot=4, prefill_buckets=(16, 32), mesh=mesh_cfg,
        ))
        res = eng.generate(GenerationRequest(
            id="pp1", prompt="hello pipeline world",
            options={"temperature": 0, "num_predict": 6, "seed": 42},
        ))
        assert res.done_reason in ("stop", "length")
        return res.token_ids

    base = run(None)
    pp = run(MeshConfig(pp=2, dp=2, tp=2))
    assert base == pp


def test_pp_engine_rejects_decoder_embeddings():
    from gridllm_tpu.engine import EngineConfig, InferenceEngine

    eng = InferenceEngine(EngineConfig(
        model="tiny-llama", max_slots=2, page_size=8, num_pages=32,
        max_pages_per_slot=4, prefill_buckets=(16, 32),
        mesh=MeshConfig(pp=2, dp=2, tp=2),
    ))
    with pytest.raises(RuntimeError, match="pipeline"):
        eng.embed(["hello"])


@pytest.mark.parametrize("n_slots", [4, 3])  # 4 → microbatched, 3 → fallback
def test_pp_decode_schedules_match_single_device(pp_mesh, n_slots):
    """Both decode schedules (GPipe microbatched when S % pp == 0, the
    sequential fallback otherwise) must match the unsharded decode for
    MULTIPLE active slots with ragged lengths."""
    params = llama.init_params(CFG, jax.random.PRNGKey(5), dtype=jnp.float32)
    cache = PagedKVCache.create(
        CFG.num_layers, num_pages=16, page_size=8,
        num_kv_heads=CFG.num_kv_heads, head_dim=CFG.head_dim_,
        max_slots=n_slots, max_pages_per_slot=4, dtype=jnp.float32)
    alloc = PageAllocator(16, 8, 4)
    # ragged prefixes in every slot
    ref_cache = cache
    for slot, ln in enumerate([5, 9, 2, 7][:n_slots]):
        alloc.alloc(slot, 16)
        row = jnp.asarray(alloc.table_row(slot), jnp.int32)
        ids = jnp.asarray(list(range(2, 2 + 16)), jnp.int32)
        _, ref_cache = llama.prefill(
            params, CFG, ids, jnp.int32(ln), ref_cache, jnp.int32(slot), row)

    tok = jnp.asarray(list(range(40, 40 + n_slots)), jnp.int32)
    act = jnp.ones((n_slots,), bool)
    ref_dec, ref_after = llama.decode_step(params, CFG, tok, ref_cache, act)

    sp_params = shard_params(params, pp_mesh)
    pp_cache = shard_cache(ref_cache, pp_mesh)
    pp_dec, pp_after = pipeline.decode_step(
        sp_params, CFG, tok, pp_cache, act, mesh=pp_mesh)
    np.testing.assert_allclose(
        np.asarray(pp_dec), np.asarray(ref_dec), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(pp_after.k), np.asarray(ref_after.k),
        rtol=2e-4, atol=2e-4)
    np.testing.assert_array_equal(
        np.asarray(pp_after.lengths), np.asarray(ref_after.lengths))
