"""Engine tests: continuous batching, Ollama option semantics, streaming,
checkpoint round-trip. All on tiny-llama with the byte tokenizer (no
external artifacts; SURVEY.md §4 test plan)."""

import numpy as np
import pytest

from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine

TINY = dict(
    model="tiny-llama",
    max_slots=4,
    page_size=8,
    num_pages=64,
    max_pages_per_slot=8,
    prefill_buckets=(16, 32),
)


@pytest.fixture(scope="module")
def engine():
    return InferenceEngine(EngineConfig(**TINY))


def test_generate_greedy_deterministic(engine):
    opts = {"temperature": 0.0, "num_predict": 8}
    r1 = engine.generate(GenerationRequest(id="a", prompt="hello", options=opts))
    r2 = engine.generate(GenerationRequest(id="b", prompt="hello", options=opts))
    assert r1.token_ids == r2.token_ids
    assert r1.eval_count == 8
    assert r1.done_reason == "length"
    assert r1.prompt_eval_count == len("hello") + 1  # + BOS
    assert r1.total_duration_ns > 0 and r1.prompt_eval_duration_ns > 0


def test_seeded_sampling_deterministic_unseeded_varies(engine):
    opts = {"temperature": 1.0, "num_predict": 12, "seed": 42}
    r1 = engine.generate(GenerationRequest(id="s1", prompt="xyz", options=opts))
    r2 = engine.generate(GenerationRequest(id="s2", prompt="xyz", options=opts))
    assert r1.token_ids == r2.token_ids
    # unseeded requests must NOT be identical across runs (review finding:
    # seed 0 default would make every request deterministic)
    free = {"temperature": 1.0, "num_predict": 12}
    outs = {
        tuple(engine.generate(
            GenerationRequest(id=f"u{i}", prompt="xyz", options=free)).token_ids)
        for i in range(4)
    }
    assert len(outs) > 1


def test_streaming_chunks_concatenate_to_text(engine):
    chunks = []
    req = GenerationRequest(
        id="st", prompt="abc", options={"temperature": 0, "num_predict": 10},
        on_chunk=lambda d, done, res: chunks.append((d, done)),
    )
    res = engine.generate(req)
    assert "".join(d for d, _ in chunks) == res.text
    assert chunks[-1][1] is True
    assert all(not done for _, done in chunks[:-1])


def test_continuous_batching_matches_solo(engine):
    """N concurrent greedy requests produce exactly their solo outputs."""
    opts = {"temperature": 0.0, "num_predict": 6}
    solo = {
        p: engine.generate(GenerationRequest(id=p, prompt=p, options=opts)).token_ids
        for p in ("aa", "bbbb", "ccccc")
    }
    results = {}

    def mk(p):
        def cb(d, done, res):
            if done:
                results[p] = res.token_ids
        return cb

    for p in solo:
        engine.submit(GenerationRequest(id=p, prompt=p, options=opts, on_chunk=mk(p)))
    while len(results) < len(solo):
        engine.step()
    assert results == solo


def test_stop_sequence_trims_and_holds_back(engine):
    base = engine.generate(
        GenerationRequest(id="q0", prompt="qq", options={"temperature": 0, "num_predict": 12})
    )
    if len(base.text) < 3:
        pytest.skip("greedy output too short to carve a stop token from")
    stop = base.text[2:4]
    chunks = []
    res = engine.generate(GenerationRequest(
        id="q1", prompt="qq",
        options={"temperature": 0, "num_predict": 12, "stop": [stop]},
        on_chunk=lambda d, done, r: chunks.append(d),
    ))
    assert stop not in res.text
    assert res.text == base.text[: base.text.find(stop)]
    assert "".join(chunks) == res.text  # nothing beyond the stop ever emitted
    assert res.done_reason == "stop"


def test_num_predict_negative_runs_to_capacity(engine):
    res = engine.generate(GenerationRequest(
        id="cap", prompt="zz", options={"temperature": 0, "num_predict": -1}
    ))
    # tiny pool: 8 pages × 8 tokens per slot = 64-token ceiling
    assert res.done_reason in ("stop", "length")
    assert res.prompt_eval_count + res.eval_count <= 64


def test_oversized_prompt_truncates_left(engine):
    long_prompt = "x" * 200  # > max_context of 64
    res = engine.generate(GenerationRequest(
        id="big", prompt=long_prompt, options={"temperature": 0, "num_predict": 2}
    ))
    assert res.done_reason == "length"
    assert res.prompt_eval_count < 64


def test_embeddings_shape_and_norm(engine):
    vecs = engine.embed(["hello", "world!"])
    assert len(vecs) == 2
    assert len(vecs[0]) == 64  # hidden_size
    assert abs(np.linalg.norm(vecs[0]) - 1.0) < 1e-3
    assert not np.allclose(vecs[0], vecs[1])


def test_checkpoint_roundtrip(tmp_path):
    from gridllm_tpu.engine.loader import load_checkpoint, save_checkpoint
    from gridllm_tpu.models.configs import get_config
    import jax.numpy as jnp

    eng = InferenceEngine(EngineConfig(**TINY))
    cfg = get_config("tiny-llama")
    save_checkpoint(eng.params, cfg, str(tmp_path))
    loaded = load_checkpoint(cfg, str(tmp_path), dtype=jnp.bfloat16)
    orig = eng.params
    for key in ("embed", "final_norm", "lm_head"):
        np.testing.assert_allclose(
            np.asarray(loaded[key], np.float32),
            np.asarray(orig[key], np.float32), rtol=1e-2, atol=1e-2,
        )
    eng2 = InferenceEngine(EngineConfig(**{**TINY, "checkpoint_path": str(tmp_path)}))
    opts = {"temperature": 0.0, "num_predict": 6}
    a = eng.generate(GenerationRequest(id="a", prompt="hi", options=opts))
    b = eng2.generate(GenerationRequest(id="b", prompt="hi", options=opts))
    assert a.token_ids == b.token_ids


def test_chunked_prefill_matches_single_shot():
    """VERDICT.md #4: prompts longer than prefill_chunk run as repeated
    fixed-shape chunk programs against the cached prefix. Greedy output must
    match the single-shot bucket path, and admitting a second long prompt of
    a DIFFERENT length must compile nothing new."""
    chunked = InferenceEngine(EngineConfig(**TINY, prefill_chunk=16))
    single = InferenceEngine(EngineConfig(**TINY, prefill_chunk=64))
    opts = {"temperature": 0.0, "num_predict": 6}

    # the chunk program: the ragged mixed step (ISSUE 6) when ragged
    # attention is on, the legacy per-chunk prefill otherwise
    chunk_fn = (chunked._mixed_chunk_fn if chunked._use_mixed
                else chunked._prefill_chunk_fn)

    prompt = "abcdefgh" * 4  # 33 ids with BOS > chunk 16 → 3 chunks
    r_c = chunked.generate(GenerationRequest(id="c", prompt=prompt, options=opts))
    r_s = single.generate(GenerationRequest(id="s", prompt=prompt, options=opts))
    assert r_c.token_ids == r_s.token_ids
    assert chunk_fn._cache_size() == 1

    # different long length → same compiled program, no new trace
    prompt2 = "zyxwvuts" * 5  # 41 ids
    r2_c = chunked.generate(GenerationRequest(id="c2", prompt=prompt2, options=opts))
    r2_s = single.generate(GenerationRequest(id="s2", prompt=prompt2, options=opts))
    assert r2_c.token_ids == r2_s.token_ids
    assert chunk_fn._cache_size() == 1


def test_embed_batched_matches_single():
    """Batched embeddings (BASELINE config #5) must equal one-at-a-time
    results for every text, across length buckets within one call."""
    eng = InferenceEngine(EngineConfig(**TINY))
    texts = ["a", "hello world", "x" * 30, "medium length text", "b" * 12]
    batched = eng.embed(texts)
    singles = [eng.embed([t])[0] for t in texts]
    for got, want in zip(batched, singles):
        np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
    # unit-norm (bf16 forward → loose tolerance)
    for v in batched:
        assert abs(float(np.linalg.norm(v)) - 1.0) < 5e-2


def test_abort_all_preserves_streamed_text():
    """A failing engine must not rewrite already-streamed text: the final
    result's text stays the concatenation of emitted deltas, and the
    failure message rides res.error (round-1 advisor finding)."""
    eng = InferenceEngine(EngineConfig(**TINY))
    seen: list[tuple[str, bool, object]] = []
    req = GenerationRequest(
        id="x", prompt="hello", options={"temperature": 0.0, "num_predict": 8},
        on_chunk=lambda d, done, res: seen.append((d, done, res)),
    )
    eng.submit(req)
    for _ in range(3):  # admit + a couple of decode steps
        eng.step()
    n = eng.abort_all("boom")
    assert n == 1
    final = seen[-1][2]
    assert final.done_reason == "error"
    assert final.error == "boom"
    streamed = "".join(d for d, _, _ in seen)
    assert streamed == final.text


def test_reset_device_state_recovers():
    """reset_device_state rebuilds donated/poisoned device buffers; the
    engine serves correctly afterwards."""
    eng = InferenceEngine(EngineConfig(**TINY))
    opts = {"temperature": 0.0, "num_predict": 4}
    before = eng.generate(GenerationRequest(id="a", prompt="hi", options=opts))
    # simulate a poisoned cache (what a mid-jit failure leaves behind)
    eng.cache.k.delete()
    eng.reset_device_state()
    after = eng.generate(GenerationRequest(id="b", prompt="hi", options=opts))
    assert before.token_ids == after.token_ids


def test_runner_streams_between_admissions():
    """VERDICT r03 #2/#3: with the runner active, an in-flight stream keeps
    producing tokens while later requests are admitted (bounded admission —
    running streams must not stall for an arrival burst), and concurrent
    streaming requests all complete with per-request live deltas."""
    import threading
    import time as _time

    eng = InferenceEngine(EngineConfig(**TINY, decode_block=2,
                                       admit_per_block=1))
    eng.start()
    try:
        events: list[tuple[str, float]] = []
        done = threading.Event()
        ndone = [0]

        def mk(name, n_total):
            def cb(d, is_done, res):
                if d:
                    events.append((name, _time.perf_counter()))
                if is_done:
                    ndone[0] += 1
                    if ndone[0] == n_total:
                        done.set()
            return cb

        opts = {"temperature": 0.0, "num_predict": 24}
        eng.submit(GenerationRequest(id="a", prompt="aaaa", options=opts,
                                     on_chunk=mk("a", 3)))
        # let "a" start streaming, then add two more mid-flight
        _time.sleep(0.3)
        eng.submit(GenerationRequest(id="b", prompt="bbbb", options=opts,
                                     on_chunk=mk("b", 3)))
        eng.submit(GenerationRequest(id="c", prompt="cccc", options=opts,
                                     on_chunk=mk("c", 3)))
        assert done.wait(timeout=60), "streams did not complete"
        firsts = {}
        for name, t in events:
            firsts.setdefault(name, t)
        # "a" streamed strictly before b/c joined, and kept streaming after
        a_times = [t for n, t in events if n == "a"]
        assert firsts["a"] < firsts["b"] and firsts["a"] < firsts["c"]
        assert max(a_times) > max(firsts["b"], firsts["c"]), (
            "stream 'a' stalled during the admission burst"
        )
    finally:
        eng.stop()


def test_runner_matches_sync_step_tokens():
    """Block-pipelined runner output must be token-identical to the sync
    step() path (same seeds, same prompts)."""
    opts = {"temperature": 0.8, "num_predict": 10, "seed": 7}
    e1 = InferenceEngine(EngineConfig(**TINY))
    want = e1.generate(GenerationRequest(id="w", prompt="hello", options=opts))
    e2 = InferenceEngine(EngineConfig(**TINY, decode_block=4))
    e2.start()
    try:
        got = e2.generate(GenerationRequest(id="g", prompt="hello", options=opts))
    finally:
        e2.stop()
    assert got.token_ids == want.token_ids


def test_cancel_running_via_runner():
    eng = InferenceEngine(EngineConfig(**TINY, decode_block=2))
    eng.start()
    try:
        import threading
        got = {}
        evt = threading.Event()

        def cb(d, done, res):
            if done:
                got["res"] = res
                evt.set()

        eng.submit(GenerationRequest(
            id="victim", prompt="xy",
            options={"temperature": 0.0, "num_predict": -1}, on_chunk=cb,
        ))
        import time as _time
        _time.sleep(0.05)
        cancelled = eng.cancel("victim")
        assert evt.wait(timeout=30)
        if cancelled:
            assert got["res"].done_reason == "cancel"
        else:  # raced to completion before the cancel landed — legal
            assert got["res"].done_reason in ("stop", "length")
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# sampler fidelity: repeat_last_n window, top_k > 64, num_ctx (VERDICT #10)
# ---------------------------------------------------------------------------

def test_repeat_last_n_window_semantics():
    """Tokens outside the repeat_last_n window must stop being penalized:
    with a tiny window the engine's device counts track only the last N
    context tokens (llama.cpp penalty_last_n), not the whole context."""
    import numpy as np

    eng = InferenceEngine(EngineConfig(**TINY, repeat_window=8))
    eng.generate(GenerationRequest(
        id="w1", prompt="abcabcabc",
        options={"temperature": 0, "num_predict": 6, "repeat_last_n": 4},
    ))
    # after the run the slot is freed, but counts of the freed slot remain;
    # the invariant to check: at most repeat_last_n tokens counted
    total = int(np.asarray(eng.counts).sum())
    assert total <= 4, f"window leak: {total} tokens counted (cap 4)"


def test_repeat_last_n_disabled_and_full_context_differ():
    """repeat_last_n=0 disables the penalty entirely; with a strong
    repeat_penalty the outputs must diverge from the windowed default."""
    base = dict(temperature=0, num_predict=12, repeat_penalty=1.9)
    eng = InferenceEngine(EngineConfig(**TINY))
    off = eng.generate(GenerationRequest(
        id="off", prompt="xyxyxyxy", options={**base, "repeat_last_n": 0}))
    on = eng.generate(GenerationRequest(
        id="on", prompt="xyxyxyxy", options={**base, "repeat_last_n": 64}))
    # penalty off → greedy repetition allowed; on → forced divergence
    assert off.token_ids != on.token_ids


def test_top_k_above_64_not_clamped():
    """TOPK lift (was 64): top_k=100 must behave differently from top_k=1
    and the sampler must accept it without clamping to 64."""
    from gridllm_tpu.ops.sampling import TOPK, SamplingParams, sample_tokens
    import jax
    import jax.numpy as jnp

    assert TOPK >= 128
    v = 512
    logits = jax.random.normal(jax.random.PRNGKey(0), (1, v))
    sp = SamplingParams.defaults(1)
    sp = dataclasses_replace(sp, top_k=jnp.asarray([100], jnp.int32),
                             temperature=jnp.asarray([3.0], jnp.float32),
                             top_p=jnp.asarray([1.0], jnp.float32),
                             repeat_penalty=jnp.asarray([1.0], jnp.float32))
    # with a hot temperature and 100 candidates, 40 seeded draws should
    # produce well over 40 distinct... at least more than top_k=1 would
    seen = set()
    for s in range(40):
        spi = dataclasses_replace(sp, seed=jnp.asarray([s], jnp.int32))
        seen.add(int(sample_tokens(logits, spi)[0]))
    assert len(seen) > 10  # far beyond a 1-token or broken-clamp regime


def dataclasses_replace(sp, **kw):
    import dataclasses
    return dataclasses.replace(sp, **kw)


def test_num_ctx_caps_request_context():
    """options.num_ctx caps the slot's context: prompt truncates from the
    left and generation stops at the cap (VERDICT r03 weak #7)."""
    eng = InferenceEngine(EngineConfig(**TINY))
    res = eng.generate(GenerationRequest(
        id="nc", prompt="x" * 100,
        options={"temperature": 0, "num_predict": -1, "num_ctx": 16},
    ))
    assert res.prompt_eval_count < 16
    assert res.prompt_eval_count + res.eval_count <= 16
    assert res.done_reason in ("stop", "length")
