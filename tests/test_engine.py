"""Engine tests: continuous batching, Ollama option semantics, streaming,
checkpoint round-trip. All on tiny-llama with the byte tokenizer (no
external artifacts; SURVEY.md §4 test plan)."""

import numpy as np
import pytest

from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine

TINY = dict(
    model="tiny-llama",
    max_slots=4,
    page_size=8,
    num_pages=64,
    max_pages_per_slot=8,
    prefill_buckets=(16, 32),
)


@pytest.fixture(scope="module")
def engine():
    return InferenceEngine(EngineConfig(**TINY))


def test_generate_greedy_deterministic(engine):
    opts = {"temperature": 0.0, "num_predict": 8}
    r1 = engine.generate(GenerationRequest(id="a", prompt="hello", options=opts))
    r2 = engine.generate(GenerationRequest(id="b", prompt="hello", options=opts))
    assert r1.token_ids == r2.token_ids
    assert r1.eval_count == 8
    assert r1.done_reason == "length"
    assert r1.prompt_eval_count == len("hello") + 1  # + BOS
    assert r1.total_duration_ns > 0 and r1.prompt_eval_duration_ns > 0


def test_seeded_sampling_deterministic_unseeded_varies(engine):
    opts = {"temperature": 1.0, "num_predict": 12, "seed": 42}
    r1 = engine.generate(GenerationRequest(id="s1", prompt="xyz", options=opts))
    r2 = engine.generate(GenerationRequest(id="s2", prompt="xyz", options=opts))
    assert r1.token_ids == r2.token_ids
    # unseeded requests must NOT be identical across runs (review finding:
    # seed 0 default would make every request deterministic)
    free = {"temperature": 1.0, "num_predict": 12}
    outs = {
        tuple(engine.generate(
            GenerationRequest(id=f"u{i}", prompt="xyz", options=free)).token_ids)
        for i in range(4)
    }
    assert len(outs) > 1


def test_streaming_chunks_concatenate_to_text(engine):
    chunks = []
    req = GenerationRequest(
        id="st", prompt="abc", options={"temperature": 0, "num_predict": 10},
        on_chunk=lambda d, done, res: chunks.append((d, done)),
    )
    res = engine.generate(req)
    assert "".join(d for d, _ in chunks) == res.text
    assert chunks[-1][1] is True
    assert all(not done for _, done in chunks[:-1])


def test_continuous_batching_matches_solo(engine):
    """N concurrent greedy requests produce exactly their solo outputs."""
    opts = {"temperature": 0.0, "num_predict": 6}
    solo = {
        p: engine.generate(GenerationRequest(id=p, prompt=p, options=opts)).token_ids
        for p in ("aa", "bbbb", "ccccc")
    }
    results = {}

    def mk(p):
        def cb(d, done, res):
            if done:
                results[p] = res.token_ids
        return cb

    for p in solo:
        engine.submit(GenerationRequest(id=p, prompt=p, options=opts, on_chunk=mk(p)))
    while len(results) < len(solo):
        engine.step()
    assert results == solo


def test_stop_sequence_trims_and_holds_back(engine):
    base = engine.generate(
        GenerationRequest(id="q0", prompt="qq", options={"temperature": 0, "num_predict": 12})
    )
    if len(base.text) < 3:
        pytest.skip("greedy output too short to carve a stop token from")
    stop = base.text[2:4]
    chunks = []
    res = engine.generate(GenerationRequest(
        id="q1", prompt="qq",
        options={"temperature": 0, "num_predict": 12, "stop": [stop]},
        on_chunk=lambda d, done, r: chunks.append(d),
    ))
    assert stop not in res.text
    assert res.text == base.text[: base.text.find(stop)]
    assert "".join(chunks) == res.text  # nothing beyond the stop ever emitted
    assert res.done_reason == "stop"


def test_num_predict_negative_runs_to_capacity(engine):
    res = engine.generate(GenerationRequest(
        id="cap", prompt="zz", options={"temperature": 0, "num_predict": -1}
    ))
    # tiny pool: 8 pages × 8 tokens per slot = 64-token ceiling
    assert res.done_reason in ("stop", "length")
    assert res.prompt_eval_count + res.eval_count <= 64


def test_oversized_prompt_truncates_left(engine):
    long_prompt = "x" * 200  # > max_context of 64
    res = engine.generate(GenerationRequest(
        id="big", prompt=long_prompt, options={"temperature": 0, "num_predict": 2}
    ))
    assert res.done_reason == "length"
    assert res.prompt_eval_count < 64


def test_embeddings_shape_and_norm(engine):
    vecs = engine.embed(["hello", "world!"])
    assert len(vecs) == 2
    assert len(vecs[0]) == 64  # hidden_size
    assert abs(np.linalg.norm(vecs[0]) - 1.0) < 1e-3
    assert not np.allclose(vecs[0], vecs[1])


def test_checkpoint_roundtrip(tmp_path):
    from gridllm_tpu.engine.loader import load_checkpoint, save_checkpoint
    from gridllm_tpu.models.configs import get_config
    import jax.numpy as jnp

    eng = InferenceEngine(EngineConfig(**TINY))
    cfg = get_config("tiny-llama")
    save_checkpoint(eng.params, cfg, str(tmp_path))
    loaded = load_checkpoint(cfg, str(tmp_path), dtype=jnp.bfloat16)
    orig = eng.params
    for key in ("embed", "final_norm", "lm_head"):
        np.testing.assert_allclose(
            np.asarray(loaded[key], np.float32),
            np.asarray(orig[key], np.float32), rtol=1e-2, atol=1e-2,
        )
    eng2 = InferenceEngine(EngineConfig(**{**TINY, "checkpoint_path": str(tmp_path)}))
    opts = {"temperature": 0.0, "num_predict": 6}
    a = eng.generate(GenerationRequest(id="a", prompt="hi", options=opts))
    b = eng2.generate(GenerationRequest(id="b", prompt="hi", options=opts))
    assert a.token_ids == b.token_ids
