"""Llava vision-language family: goldens vs HF + engine e2e.

SURVEY.md §4 test strategy (engine numeric goldens vs HF twins) applied
to the vision path (VERDICT r03 missing #5): the torch twin is
transformers' LlavaForConditionalGeneration on the tiny-llava config.
"""

import base64
import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gridllm_tpu.models import llava
from gridllm_tpu.models.configs import get_config

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def twin():
    """(our cfg, our fp32 params, HF model) with identical weights."""
    cfg = get_config("tiny-llava")
    hf_cfg = cfg.hf_config()
    torch.manual_seed(0)
    with torch.no_grad():
        model = transformers.LlavaForConditionalGeneration(hf_cfg).eval()
    params = llava.convert_hf_state_dict(cfg, model.state_dict(), jnp.float32)
    return cfg, params, model


def _pixels(n, cfg, seed=0):
    rng = np.random.default_rng(seed)
    s = cfg.vision_cfg.image_size
    return rng.normal(size=(n, 3, s, s)).astype(np.float32)


def test_vision_tower_matches_hf(twin):
    cfg, params, model = twin
    px = _pixels(2, cfg)
    ours = np.asarray(llava.vision_tower(params, cfg.vision_cfg, jnp.asarray(px)))
    with torch.no_grad():
        theirs = model.model.vision_tower(
            torch.from_numpy(px), output_hidden_states=True
        ).hidden_states[cfg.vision_cfg.feature_layer][:, 1:].numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_encode_images_matches_hf(twin):
    cfg, params, model = twin
    px = _pixels(1, cfg)
    ours = np.asarray(llava.encode_images(params, cfg, jnp.asarray(px)))
    with torch.no_grad():
        theirs = model.get_image_features(
            pixel_values=torch.from_numpy(px),
            vision_feature_layer=cfg.vision_cfg.feature_layer,
            vision_feature_select_strategy="default",
        )
    theirs = (theirs[0] if isinstance(theirs, (tuple, list)) else theirs).numpy()
    theirs = theirs.reshape(ours.shape)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_full_forward_matches_hf(twin):
    """End-to-end logits: expanded image tokens + splice == HF's
    masked-scatter of image features."""
    cfg, params, model = twin
    vc = cfg.vision_cfg
    px = _pixels(1, cfg)
    rng = np.random.default_rng(1)
    text = rng.integers(0, 240, size=(7,))
    ids = np.concatenate([
        text[:3], np.full((vc.num_patches,), vc.image_token), text[3:],
    ]).astype(np.int32)

    img = llava.encode_images(params, cfg, jnp.asarray(px))
    flat = img.reshape(-1, img.shape[-1])
    embeds = llava.splice_embeds(params, cfg, jnp.asarray(ids), flat)
    ours = np.asarray(
        llava.forward(params, cfg, jnp.asarray(ids)[None], embeds=embeds[None])
    )[0]

    with torch.no_grad():
        out = model(
            input_ids=torch.from_numpy(ids[None].astype(np.int64)),
            pixel_values=torch.from_numpy(px),
        ).logits[0].float().numpy()
    np.testing.assert_allclose(ours, out, rtol=2e-3, atol=2e-3)


def test_splice_offset_chunks_agree(twin):
    """Chunked splice (per-chunk offset) == whole-prompt splice."""
    cfg, params, _ = twin
    vc = cfg.vision_cfg
    ids = np.array(
        [1, 2] + [vc.image_token] * vc.num_patches + [3]
        + [vc.image_token] * vc.num_patches + [4, 5], np.int32)
    flat = jnp.asarray(
        np.random.default_rng(2).normal(
            size=(2 * vc.num_patches, cfg.hidden_size)).astype(np.float32))
    whole = np.asarray(llava.splice_embeds(params, cfg, jnp.asarray(ids), flat))
    c = 4
    parts = []
    for s0 in range(0, len(ids), c):
        part = ids[s0:s0 + c]
        off = int((ids[:s0] == vc.image_token).sum())
        parts.append(np.asarray(llava.splice_embeds(
            params, cfg, jnp.asarray(part), flat, offset=off)))
    np.testing.assert_allclose(np.concatenate(parts), whole, rtol=1e-6, atol=1e-6)


def test_preprocess_matches_hf_processor():
    from gridllm_tpu.engine.images import preprocess_images

    PIL = pytest.importorskip("PIL")
    from PIL import Image

    rng = np.random.default_rng(3)
    img = Image.fromarray(rng.integers(0, 255, (50, 41, 3), np.uint8))
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    b64 = base64.b64encode(buf.getvalue()).decode()

    ours = preprocess_images([b64], 28)[0]

    proc = transformers.CLIPImageProcessor(
        size={"shortest_edge": 28}, crop_size={"height": 28, "width": 28},
        do_convert_rgb=True,
    )
    theirs = proc(images=img, return_tensors="np")["pixel_values"][0]
    np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-4)


def test_engine_serves_image_request(twin):
    """Full engine path: base64 PNG in, generated tokens out; marker-free
    prompt gets the image span inserted after BOS."""
    from PIL import Image

    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.engine.engine import GenerationRequest

    rng = np.random.default_rng(4)
    img = Image.fromarray(rng.integers(0, 255, (30, 30, 3), np.uint8))
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    b64 = base64.b64encode(buf.getvalue()).decode()

    eng = InferenceEngine(EngineConfig(
        model="tiny-llava", max_slots=2, page_size=16, num_pages=64,
        max_pages_per_slot=8, prefill_buckets=(32, 64),
    ))
    res = eng.generate(GenerationRequest(
        id="img1", prompt="hi", images=[b64],
        options={"temperature": 0, "num_predict": 4, "seed": 1},
    ))
    assert res.done_reason in ("stop", "length")
    assert res.prompt_eval_count >= eng.cfg.vision_cfg.num_patches

    # same request again must be deterministic (seeded, temperature 0)
    res2 = eng.generate(GenerationRequest(
        id="img2", prompt="hi", images=[b64],
        options={"temperature": 0, "num_predict": 4, "seed": 1},
    ))
    assert res2.token_ids == res.token_ids


def test_engine_rejects_marker_mismatch(twin):
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.engine.engine import GenerationRequest

    eng = InferenceEngine(EngineConfig(
        model="tiny-llava", max_slots=2, page_size=16, num_pages=64,
        max_pages_per_slot=8, prefill_buckets=(32, 64),
    ))
    vc = eng.cfg.vision_cfg
    # two markers, one image → loud failure
    res = eng.generate(GenerationRequest(
        id="bad", prompt_ids=[1, vc.image_token, 2, vc.image_token],
        images=["aGVsbG8="],  # not even a real image; rejected before decode
        options={"num_predict": 2},
    ))
    assert res.done_reason == "error"
    assert "placeholder" in (res.error or "")


def test_non_vision_model_rejects_images():
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.engine.engine import GenerationRequest

    eng = InferenceEngine(EngineConfig(
        model="tiny-llama", max_slots=1, page_size=16, num_pages=32,
        max_pages_per_slot=4, prefill_buckets=(32,),
    ))
    res = eng.generate(GenerationRequest(
        id="noimg", prompt="x", images=["aGVsbG8="],
        options={"num_predict": 2},
    ))
    assert res.done_reason == "error"
    assert "image" in (res.error or "")


def test_context_roundtrip_requires_images(twin):
    """Ollama `context` from an image turn carries expanded image-token
    runs: re-sending it WITHOUT the pixels must fail loudly (placeholder
    embeddings would silently answer about an unseen image); re-sending
    WITH the images must work (already-expanded runs pass through)."""
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.engine.engine import GenerationRequest

    eng = InferenceEngine(EngineConfig(
        model="tiny-llava", max_slots=2, page_size=16, num_pages=64,
        max_pages_per_slot=8, prefill_buckets=(32, 64),
    ))
    vc = eng.cfg.vision_cfg
    ctx = [1, 2] + [vc.image_token] * vc.num_patches + [3]

    res = eng.generate(GenerationRequest(
        id="ctx-no-img", prompt_ids=ctx, options={"num_predict": 2}))
    assert res.done_reason == "error"
    assert "re-send" in (res.error or "")

    import base64
    import io

    import numpy as np
    from PIL import Image

    img = Image.fromarray(
        np.random.default_rng(5).integers(0, 255, (20, 20, 3), np.uint8))
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    res = eng.generate(GenerationRequest(
        id="ctx-img", prompt_ids=ctx,
        images=[base64.b64encode(buf.getvalue()).decode()],
        options={"temperature": 0, "num_predict": 2, "seed": 0}))
    assert res.done_reason in ("stop", "length")


def test_plan_replay_reproduces_vision_admission(twin):
    """Multi-host followers replay admit records; a vision admit carries
    the raw base64 payload and the follower must re-run preprocessing +
    encode + splice to land in the SAME device state as the LIAISON.
    Compared against the liaison's actual pool (prompt rows are written
    once at prefill and never touched by later decode steps), and against
    a no-image replay to prove the image actually changed the K/V."""
    import base64
    import io

    import numpy as np
    from PIL import Image

    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.engine.engine import GenerationRequest

    img = Image.fromarray(
        np.random.default_rng(6).integers(0, 255, (24, 24, 3), np.uint8))
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    b64 = base64.b64encode(buf.getvalue()).decode()

    kw = dict(model="tiny-llava", max_slots=2, page_size=16, num_pages=64,
              max_pages_per_slot=8, prefill_buckets=(32, 64))
    liaison = InferenceEngine(EngineConfig(**kw))
    follower = InferenceEngine(EngineConfig(**kw))
    records = []
    liaison.plan_sink = records.append

    res = liaison.generate(GenerationRequest(
        id="vp", prompt="look", images=[b64],
        options={"temperature": 0, "num_predict": 3, "seed": 4}))
    assert res.done_reason in ("stop", "length")
    admits = [r for r in records if r["op"] == "admit"]
    assert admits and admits[0].get("images") == [b64]
    rec = admits[0]
    n_prompt = len(rec["ids"])
    ps = kw["page_size"]
    pages = [p for p in rec["row"] if p >= 0][: -(-n_prompt // ps)]

    def prompt_rows(eng):
        # positions [0, n_prompt) of the slot, gathered from its pages
        pool = np.asarray(eng.cache.k)  # [L, P, ps, KVH, D]
        rows = np.concatenate([pool[:, p] for p in pages], axis=1)
        return rows[:, :n_prompt]

    want = prompt_rows(liaison)  # decode wrote positions >= n_prompt only

    follower.apply_plan_op(rec)
    np.testing.assert_array_equal(prompt_rows(follower), want)
    assert int(np.asarray(follower.cache.lengths)[rec["slot"]]) == n_prompt

    # and the image must MATTER: replaying with the pixels dropped gives
    # different K/V (guards against a replay path that skips the splice)
    textonly = InferenceEngine(EngineConfig(**kw))
    rec_no_img = dict(rec)
    rec_no_img.pop("images")
    textonly.apply_plan_op(rec_no_img)
    assert not np.array_equal(prompt_rows(textonly), want)
