"""Lock-discipline sanitizer units (ISSUE 8): the graph must see real
orderings, flag real cycles, ignore benign reentry/twins, and the
allocator guard must catch unguarded mutation at the call site.

These tests drive the monitor through directly constructed proxies
(``make_lock``/``make_rlock``) — no global install, so they are safe to
run alongside any other test regardless of GRIDLLM_SANITIZE.
"""

import threading

import pytest

from gridllm_tpu.analysis import lockcheck
from gridllm_tpu.analysis.lockcheck import (
    LockDisciplineError,
    guard_allocator,
    make_lock,
    make_rlock,
)
from gridllm_tpu.ops.kvcache import PageAllocator


@pytest.fixture(autouse=True)
def _fresh_graph():
    # snapshot/restore instead of plain reset: under GRIDLLM_SANITIZE=1
    # the graph is process-global and the conftest sessionfinish hook
    # judges it — these tests must not erase edges (or a real inversion!)
    # recorded by suites that ran before them
    saved = lockcheck.edges()
    lockcheck.reset()
    yield
    lockcheck.reset()
    lockcheck.restore(saved)


def _two_locks():
    # distinct creation sites: the graph keys nodes by file:line, and
    # same-site twins are deliberately not edges
    a = make_lock()
    b = make_lock()
    return a, b


def test_ordered_acquisition_is_acyclic():
    a, b = _two_locks()
    for _ in range(3):
        with a:
            with b:
                pass
    assert lockcheck.edges(), "edge a->b should have been recorded"
    assert lockcheck.cycles() == []
    lockcheck.assert_clean()


def test_inverted_acquisition_is_a_cycle():
    a, b = _two_locks()
    with a:
        with b:
            pass
    # the inversion — single-threaded here, but two threads interleaving
    # these two orders deadlock; the graph is order-sensitive, not
    # schedule-sensitive
    with b:
        with a:
            pass
    cycles = lockcheck.cycles()
    assert cycles, "a->b->a cycle must be reported"
    with pytest.raises(LockDisciplineError, match="cycle"):
        lockcheck.assert_clean()


def test_rlock_reentry_is_not_an_edge():
    r = make_rlock()
    with r:
        with r:
            pass
    assert lockcheck.edges() == {}
    assert lockcheck.cycles() == []


def test_same_site_twins_are_not_an_edge():
    def factory():
        return make_lock()  # both instances share this creation site

    a, b = factory(), factory()
    with a:
        with b:
            pass
    assert lockcheck.edges() == {}


def test_cross_thread_orders_merge_into_one_graph():
    a, b = _two_locks()

    def worker_ab():
        with a:
            with b:
                pass

    def worker_ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=worker_ab)
    t1.start()
    t1.join()
    assert lockcheck.cycles() == []
    t2 = threading.Thread(target=worker_ba)
    t2.start()
    t2.join()
    assert lockcheck.cycles(), "the two threads' orders form a cycle"


def test_cross_thread_release_drops_the_acquirers_entry():
    """Plain Lock legally allows release from another thread (handoff
    patterns). The acquirer's held stack must drop the entry anyway, or
    every later acquire on that thread records edges from a lock it no
    longer holds — fabricating cycles that cannot deadlock."""
    a, b = _two_locks()
    a.acquire()
    t = threading.Thread(target=a.release)
    t.start()
    t.join()
    with b:  # a is no longer held here: this must record no a->b edge
        pass
    # assert on the specific edge, not an empty graph: under
    # GRIDLLM_SANITIZE=1 Thread's own startup locks are proxied too and
    # record incidental (benign) edges against the lines above
    assert (a.site, b.site) not in lockcheck.edges()


def test_restore_merges_snapshotted_edges_back():
    """The autouse fixture must hand back what earlier suites recorded —
    a sanitized session's final verdict covers them, not just us."""
    a, b = _two_locks()
    with a:
        with b:
            pass
    saved = lockcheck.edges()
    assert saved
    lockcheck.reset()
    assert lockcheck.edges() == {}
    lockcheck.restore(saved)
    assert lockcheck.edges() == saved


def test_guard_allocator_rejects_unlocked_mutation():
    alloc = PageAllocator(8, 4, 4)
    lock = threading.RLock()
    guard_allocator(alloc, lock)
    with pytest.raises(LockDisciplineError, match="_alloc_lock"):
        alloc.alloc(0, 4)
    # under the lock the same call goes through untouched
    with lock:
        pages = alloc.alloc(0, 4)
    assert pages


def test_guard_allocator_leaves_reads_and_other_instances_alone():
    guarded = PageAllocator(8, 4, 4)
    unguarded = PageAllocator(8, 4, 4)
    lock = threading.RLock()
    guard_allocator(guarded, lock)
    # reads never need the lock
    assert guarded.free_pages == 8
    assert guarded.can_fit(4)
    # a different instance (unit tests poking the allocator) is untouched
    assert unguarded.alloc(0, 4)


def test_engine_guard_is_wired(monkeypatch):
    """GRIDLLM_SANITIZE=1 at engine construction guards the engine's own
    allocator — the integration point conftest+CI rely on."""
    monkeypatch.setenv("GRIDLLM_SANITIZE", "1")
    from gridllm_tpu.engine.engine import EngineConfig, InferenceEngine

    eng = InferenceEngine(EngineConfig(
        model="tiny-llama", max_slots=2, page_size=8, num_pages=32,
        max_pages_per_slot=4, prefill_buckets=(16,),
    ))
    assert getattr(eng.alloc, "_sanitize_guarded", False)
    with pytest.raises(LockDisciplineError):
        eng.alloc.alloc(0, 8)
    with eng._alloc_lock:
        assert eng.alloc.alloc(0, 8)
        eng.alloc.free(0)
