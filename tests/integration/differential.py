#!/usr/bin/env python
"""Differential API-shape e2e — the fidelity gate for drop-in parity.

Port of the reference's tests/integration/integration.ts:1-224: issue the
same request to an oracle and to GridLLM-TPU and compare response SHAPE —
same key set and same `typeof` per key, values ignored
(areObjectsSimilar, integration.ts:6-35). Covers /v1/models,
/v1/completions, /v1/chat/completions incl. tool definitions
(integration.ts:37-173), plus /ollama/api/generate.

Oracle selection:
- OLLAMA_ENDPOINT set → live differential against a real Ollama (exactly
  the reference's CI harness).
- otherwise → recorded golden shapes below, captured from real Ollama
  0.6.x / OpenAI-compat responses (zero-egress CI can still gate shape).

Usage: python tests/integration/differential.py \
         --endpoint http://localhost:4000 --model tiny-llama
Exit code 0 = all shape checks passed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

# JS typeof buckets (integration.ts compares `typeof`): bool is its own
# type in JS ("boolean"), int/float are both "number", None ~ "object".
def _js_typeof(v) -> str:
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, float)):
        return "number"
    if isinstance(v, str):
        return "string"
    return "object"


def are_objects_similar(a, b, path="$") -> bool:
    """Same sorted key set + same typeof per key (values ignored)."""
    ka, kb = sorted(a.keys()), sorted(b.keys())
    if ka != kb:
        print(f"Keys mismatch at {path}:", {"oracle": ka, "gridllm": kb})
        return False
    ok = True
    for k in ka:
        if _js_typeof(a[k]) != _js_typeof(b[k]):
            print(f'Type mismatch for key "{path}.{k}":',
                  {"oracle": _js_typeof(a[k]), "gridllm": _js_typeof(b[k])})
            ok = False
    return ok


# ------------------------------------------------------------------ goldens
# Shapes recorded from real Ollama (native + OpenAI facade) responses.

GOLDEN = {
    "v1_models": {
        "object": "list",
        "data": [
            {"id": "m", "object": "model", "created": 0, "owned_by": "library"},
        ],
    },
    "v1_completions": {
        "id": "cmpl-x", "object": "text_completion", "created": 0,
        "model": "m", "system_fingerprint": "fp_ollama",
        "choices": [
            {"index": 0, "text": "t", "logprobs": None,
             "finish_reason": "stop"},
        ],
        "usage": {"prompt_tokens": 1, "completion_tokens": 1, "total_tokens": 2},
    },
    "v1_chat_completions": {
        "id": "chatcmpl-x", "object": "chat.completion", "created": 0,
        "model": "m", "system_fingerprint": "fp_ollama",
        "choices": [
            {"index": 0,
             "message": {"role": "assistant", "content": "t"},
             "logprobs": None,
             "finish_reason": "stop"},
        ],
        "usage": {"prompt_tokens": 1, "completion_tokens": 1, "total_tokens": 2},
    },
    "ollama_generate": {
        "model": "m", "created_at": "2024-01-01T00:00:00Z", "response": "t",
        "done": True, "done_reason": "stop", "context": [1],
        "total_duration": 1, "load_duration": 1, "prompt_eval_count": 1,
        "prompt_eval_duration": 1, "eval_count": 1, "eval_duration": 1,
    },
    "ollama_chat": {
        "model": "m", "created_at": "2024-01-01T00:00:00Z",
        "message": {"role": "assistant", "content": "t"},
        "done": True, "done_reason": "stop",
        "total_duration": 1, "load_duration": 1, "prompt_eval_count": 1,
        "prompt_eval_duration": 1, "eval_count": 1, "eval_duration": 1,
    },
}


def _post(url: str, payload: dict) -> dict:
    req = urllib.request.Request(
        url, json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def _get(url: str) -> dict:
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def run(endpoint: str, model: str, oracle: str | None) -> bool:
    results: dict[str, bool] = {}

    def oracle_shape(name: str, fetch):
        if oracle:
            return fetch(oracle)
        return GOLDEN[name]

    # /v1/models (integration.ts:37-48)
    got = _get(f"{endpoint}/v1/models")
    want = oracle_shape("v1_models", lambda o: _get(f"{o}/v1/models"))
    ok = are_objects_similar(want, got)
    if ok and not oracle and got.get("data") and want.get("data"):
        ok = are_objects_similar(want["data"][0], got["data"][0], "$.data[0]")
    results["/v1/models"] = ok

    # /v1/completions (integration.ts:50-81)
    comp_req = {"model": model, "prompt": "Hello, world!", "max_tokens": 5,
                "temperature": 0}
    got = _post(f"{endpoint}/v1/completions", comp_req)
    want = oracle_shape(
        "v1_completions", lambda o: _post(f"{o}/v1/completions", comp_req)
    )
    ok = are_objects_similar(want, got)
    if ok and not oracle:
        ok = are_objects_similar(want["choices"][0], got["choices"][0],
                                 "$.choices[0]")
        ok = ok and are_objects_similar(want["usage"], got["usage"], "$.usage")
    results["/v1/completions"] = ok

    # /v1/chat/completions with tool definitions (integration.ts:83-173)
    chat_req = {
        "model": model, "max_tokens": 8, "temperature": 0,
        "messages": [{"role": "user", "content": "What is 2+2?"}],
        "tools": [{
            "type": "function",
            "function": {
                "name": "calculator",
                "description": "Evaluate arithmetic",
                "parameters": {
                    "type": "object",
                    "properties": {"expression": {"type": "string"}},
                    "required": ["expression"],
                },
            },
        }],
    }
    got = _post(f"{endpoint}/v1/chat/completions", chat_req)
    want = oracle_shape(
        "v1_chat_completions",
        lambda o: _post(f"{o}/v1/chat/completions", chat_req),
    )
    ok = are_objects_similar(want, got)
    if ok and not oracle:
        ok = are_objects_similar(want["choices"][0], got["choices"][0],
                                 "$.choices[0]")
        ok = ok and are_objects_similar(
            want["choices"][0]["message"], got["choices"][0]["message"],
            "$.choices[0].message",
        )
    results["/v1/chat/completions"] = ok

    # /ollama/api/generate non-streaming (native API shape)
    gen_req = {"model": model, "prompt": "Hi", "stream": False,
               "options": {"num_predict": 4, "temperature": 0}}
    got = _post(f"{endpoint}/ollama/api/generate", gen_req)
    want = oracle_shape(
        "ollama_generate", lambda o: _post(f"{o}/api/generate", gen_req)
    )
    results["/ollama/api/generate"] = are_objects_similar(want, got)

    # /ollama/api/generate with the full option surface the reference
    # forwarded (OllamaService.ts:197-226): system + template + format +
    # sampler knobs must be APPLIED without changing the response shape
    # (VERDICT r03 missing #2 — options were accepted and ignored)
    opt_req = {
        "model": model, "prompt": "List two colors", "stream": False,
        "system": "You are terse.",
        "template": "{{ if .System }}{{ .System }}\n{{ end }}{{ .Prompt }}",
        "format": "json",
        "options": {"num_predict": 8, "temperature": 0, "num_ctx": 64,
                    "repeat_last_n": 16, "top_k": 100},
    }
    got = _post(f"{endpoint}/ollama/api/generate", opt_req)
    want = oracle_shape(
        "ollama_generate", lambda o: _post(f"{o}/api/generate", opt_req)
    )
    results["/ollama/api/generate+options"] = are_objects_similar(want, got)

    # /ollama/api/chat non-streaming with a system message (native shape)
    chat_native = {
        "model": model, "stream": False,
        "messages": [
            {"role": "system", "content": "Be brief."},
            {"role": "user", "content": "Hello"},
        ],
        "options": {"num_predict": 4, "temperature": 0},
    }
    got = _post(f"{endpoint}/ollama/api/chat", chat_native)
    want = oracle_shape(
        "ollama_chat", lambda o: _post(f"{o}/api/chat", chat_native)
    )
    results["/ollama/api/chat"] = are_objects_similar(want, got)

    print()
    for name, ok in results.items():
        print(f"  {'PASS' if ok else 'FAIL'}  {name}")
    return all(results.values())


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--endpoint",
                    default=os.environ.get("GRIDLLM_ENDPOINT",
                                           "http://localhost:4000"))
    ap.add_argument("--model",
                    default=os.environ.get("TEST_MODEL", "tiny-llama"))
    ap.add_argument("--oracle", default=os.environ.get("OLLAMA_ENDPOINT"))
    args = ap.parse_args()
    ok = run(args.endpoint, args.model, args.oracle)
    print("\nALL SHAPE CHECKS PASSED" if ok else "\nSHAPE CHECKS FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
