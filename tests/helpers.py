"""Fake worker speaking the §2.6 bus protocol — scheduling/failover tests
need no TPU and no model (SURVEY.md §4 'rebuild test plan implications')."""

from __future__ import annotations

import asyncio
import json
import time

from gridllm_tpu.bus.base import MessageBus
from gridllm_tpu.utils.config import SchedulerConfig
from gridllm_tpu.utils.types import (
    InferenceResponse,
    JobAssignment,
    JobResult,
    ModelInfo,
    NodeCapabilities,
    StreamChunk,
    WorkerInfo,
    iso_now,
)


def fast_config() -> SchedulerConfig:
    """Sub-second timers so failure-path tests run quickly."""
    return SchedulerConfig(
        worker_heartbeat_timeout_ms=600,
        worker_cleanup_interval_ms=100,
        connection_monitor_interval_ms=100,
        quick_disconnect_window_ms=400,
        orphan_assign_threshold_ms=200,
        job_timeout_ms=5_000,
        retry_attempts=2,
        retry_delay_ms=50,
        sweep_interval_ms=100,
    )


class FakeWorker:
    """Registers, heartbeats, executes canned jobs over the bus protocol."""

    def __init__(self, bus: MessageBus, worker_id: str, models: list[str],
                 max_concurrent: int = 1, heartbeat_interval_s: float = 0.2,
                 reply: str = "canned response", delay_s: float = 0.0,
                 fail_times: int = 0, stream_tokens: list[str] | None = None,
                 fail_retryable: bool = True, nack_times: int = 0,
                 layouts: list | None = None, stream_delay_s: float = 0.0):
        self.bus = bus
        self.worker_id = worker_id
        self.models = models
        self.max_concurrent = max_concurrent
        self.heartbeat_interval_s = heartbeat_interval_s
        self.reply = reply
        self.delay_s = delay_s
        self.fail_times = fail_times
        self.fail_retryable = fail_retryable
        self.nack_times = nack_times
        self.layouts = layouts or []
        self.stream_tokens = stream_tokens
        # inter-token pause for streamed replies: chaos tests kill control-
        # plane components MID-decode, so the stream must span real time
        self.stream_delay_s = stream_delay_s
        self.current_jobs = 0
        self.processed: list[str] = []
        self.cancelled: list[str] = []
        # every job_assignment delivery, in order — the double-assignment
        # detector for the control-plane chaos differentials (ISSUE 15)
        self.assignments: list[str] = []
        self._subs = []
        self._hb_task: asyncio.Task | None = None
        self._running = False

    def _info(self) -> WorkerInfo:
        return WorkerInfo(
            workerId=self.worker_id,
            capabilities=NodeCapabilities(
                workerId=self.worker_id,
                availableModels=[ModelInfo(name=m) for m in self.models],
                maxConcurrentTasks=self.max_concurrent,
                shardLayouts=self.layouts,
            ),
            status="online",
            currentJobs=self.current_jobs,
        )

    async def start(self) -> None:
        self._running = True
        self._subs.append(await self.bus.subscribe(
            f"worker:{self.worker_id}:job", self._on_job_message))
        self._subs.append(await self.bus.subscribe(
            f"worker:reregister:{self.worker_id}", self._on_reregister))
        await self.register()
        self._hb_task = asyncio.create_task(self._heartbeat_loop())

    async def register(self) -> None:
        info = self._info()
        await self.bus.hset("workers", self.worker_id, info.model_dump_json())
        await self.bus.publish("worker:registered", info.model_dump_json())

    async def stop(self, announce: bool = True) -> None:
        """Graceful stop; announce=False simulates abrupt death."""
        self._running = False
        if self._hb_task:
            self._hb_task.cancel()
            self._hb_task = None
        for s in self._subs:
            await s.unsubscribe()
        self._subs.clear()
        if announce:
            await self.bus.publish("worker:unregistered",
                                   json.dumps({"workerId": self.worker_id}))

    async def die(self) -> None:
        """Abrupt death: no unregister, heartbeat key left to expire."""
        await self.stop(announce=False)
        await self.bus.delete(f"heartbeat:{self.worker_id}")

    async def _heartbeat_loop(self) -> None:
        while self._running:
            await self.bus.set_with_expiry(
                f"heartbeat:{self.worker_id}", str(time.time()),
                ttl_s=self.heartbeat_interval_s * 2)
            await self.bus.publish("worker:heartbeat", json.dumps({
                "workerId": self.worker_id,
                "status": "busy" if self.current_jobs >= self.max_concurrent else "online",
                "currentJobs": self.current_jobs,
            }))
            await asyncio.sleep(self.heartbeat_interval_s)

    async def _on_reregister(self, _ch: str, _raw: str) -> None:
        await self.register()

    async def _on_job_message(self, _ch: str, raw: str) -> None:
        msg = json.loads(raw)
        if msg.get("type") == "job_cancellation":
            self.cancelled.append(msg["jobId"])
            return
        if msg.get("type") != "job_assignment":
            return
        assignment = JobAssignment.model_validate(msg["job"])
        self.assignments.append(assignment.jobId)
        if self.nack_times > 0:
            self.nack_times -= 1
            result = JobResult(jobId=assignment.jobId, workerId=self.worker_id,
                               success=False, error="worker at capacity",
                               nack=True)
            asyncio.ensure_future(
                self.bus.publish("job:failed", result.model_dump_json()))
            return
        asyncio.ensure_future(self._execute(assignment))

    async def _execute(self, assignment: JobAssignment) -> None:
        self.current_jobs += 1
        start = time.time()
        job_id = assignment.jobId
        try:
            if self.delay_s:
                await asyncio.sleep(self.delay_s)
            if job_id in self.cancelled:
                return
            if self.fail_times > 0:
                self.fail_times -= 1
                result = JobResult(jobId=job_id, workerId=self.worker_id,
                                   success=False, error="injected failure",
                                   retryable=self.fail_retryable,
                                   processingTimeMs=(time.time() - start) * 1000)
                await self.bus.publish("job:failed", result.model_dump_json())
                return
            if self.stream_tokens is not None and assignment.request.stream:
                offset = 0
                for i, tok in enumerate(self.stream_tokens):
                    if self.stream_delay_s and i:
                        await asyncio.sleep(self.stream_delay_s)
                    await self.bus.publish(f"job:stream:{job_id}", StreamChunk(
                        id=job_id, model=assignment.request.model,
                        created_at=iso_now(), response=tok, done=False,
                        offset=offset,
                    ).model_dump_json())
                    offset += len(tok)
                text = "".join(self.stream_tokens)
            else:
                text = self.reply
            self.processed.append(job_id)
            response = InferenceResponse(
                id=job_id, model=assignment.request.model, created_at=iso_now(),
                response=text, done=True, done_reason="stop",
                eval_count=len(text.split()),
                total_duration=int((time.time() - start) * 1e9),
            )
            result = JobResult(jobId=job_id, workerId=self.worker_id,
                               success=True, response=response,
                               processingTimeMs=(time.time() - start) * 1000)
            await self.bus.publish("job:completed", result.model_dump_json())
            await self.bus.publish(f"job:result:{job_id}", result.model_dump_json())
        finally:
            self.current_jobs -= 1
