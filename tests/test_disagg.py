"""Disaggregated prefill/decode serving (ISSUE 7): differential,
routing, fallback, and orphan-mid-migration coverage.

The headline invariant: a request prefilled on worker A and decoded on
worker B after a KV-page migration produces a BYTE-IDENTICAL greedy
token stream to the same request served by a unified worker — warm
prefix-cache and speculative-decode paths included (speculation is
default-on, so every differential here exercises the spec path too).
The two-process RESP-broker versions (slow) add process isolation and
the kill-the-decode-worker fallback."""

from __future__ import annotations

import asyncio
import json
import subprocess
import sys
import time
import uuid
from pathlib import Path

import pytest

from gridllm_tpu.bus import InMemoryBus
from gridllm_tpu.engine import EngineConfig, InferenceEngine
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.config import SchedulerConfig, WorkerConfig
from gridllm_tpu.utils.types import InferenceRequest, JobAssignment, Priority
from gridllm_tpu.worker.service import WorkerService

CHILD = Path(__file__).with_name("disagg_worker_child.py")
MODEL = "tiny-llama"
PROMPT = "the quick brown fox jumps over the lazy dog " * 2


def make_engine(**kw) -> InferenceEngine:
    cfg = dict(
        model=MODEL, max_slots=2, page_size=8, num_pages=96,
        max_pages_per_slot=16, prefill_buckets=(16, 64, 128), seed=42,
        prefill_chunk=16,
    )
    cfg.update(kw)
    return InferenceEngine(EngineConfig(**cfg))


def fleet_config() -> SchedulerConfig:
    return SchedulerConfig(
        worker_heartbeat_timeout_ms=60_000,
        job_timeout_ms=180_000,
        sweep_interval_ms=200,
    )


class Fleet:
    """In-process serving fleet: scheduler + N real-engine workers."""

    def __init__(self, roles: list[str]):
        self.roles = roles
        self.workers: list[WorkerService] = []

    async def __aenter__(self) -> "Fleet":
        self.bus = InMemoryBus()
        await self.bus.connect()
        cfg = fleet_config()
        self.registry = WorkerRegistry(self.bus, cfg)
        self.scheduler = JobScheduler(self.bus, self.registry, cfg)
        await self.registry.initialize()
        await self.scheduler.initialize()
        for i, role in enumerate(self.roles):
            svc = WorkerService(
                self.bus, {MODEL: make_engine()},
                WorkerConfig(worker_id=f"w-{role}-{i}", role=role,
                             heartbeat_interval_ms=200),
                stream_flush_ms=5)
            await svc.start()
            self.workers.append(svc)
        await asyncio.sleep(0.5)  # first heartbeats land
        return self

    async def __aexit__(self, *exc) -> None:
        for svc in self.workers:
            await svc.stop(announce=False)
        await self.scheduler.shutdown()
        await self.registry.shutdown()
        await self.bus.disconnect()

    def disagg_count(self, event: str) -> int:
        return int(self.scheduler._disagg_total.value(event=event))

    async def run(self, prompt: str = PROMPT, n: int = 16, **opts):
        chunks: list[str] = []

        async def on_chunk(c) -> None:
            chunks.append(c.response)

        req = InferenceRequest(
            id=f"job-{uuid.uuid4().hex[:8]}", model=MODEL, prompt=prompt,
            stream=True,
            options={"temperature": 0, "num_predict": n, **opts},
            metadata={"requestType": "inference"})
        result = await self.scheduler.submit_streaming_job(
            req, on_chunk, timeout_ms=120_000)
        return "".join(chunks), result


async def test_disagg_stream_byte_identical_to_unified():
    """THE differential (acceptance criterion): prefill on A, decode on
    B, stream == unified, with a real migration (planned + handoff
    counted) and zero steady-state recompiles on both engines. A second,
    warm round (pages already cached/imported on both ends) must match
    too — the warm prefix-cache path of the migration."""
    async with Fleet(["unified"]) as uni:
        text_u1, res_u1 = await uni.run()
        text_u2, _ = await uni.run()  # warm round on the unified arm
        assert uni.disagg_count("planned") == 0

    async with Fleet(["prefill", "decode"]) as dis:
        text_d1, res_d1 = await dis.run()
        text_d2, res_d2 = await dis.run()  # warm: both ends hold the pages
        assert text_d1 == text_u1 and text_d1
        assert text_d2 == text_u2 == text_u1
        assert res_d1.workerId.startswith("w-decode")
        assert res_d2.workerId.startswith("w-decode")
        assert res_d1.response.eval_count == res_u1.response.eval_count
        assert dis.disagg_count("planned") == 2
        assert dis.disagg_count("handoff") == 2
        assert dis.disagg_count("fallback") == 0
        assert dis.disagg_count("migration_lost") == 0
        # spec decoding is default-on: the decode side really ran the
        # speculative path on migrated pages
        dec_eng = dis.workers[1].engines[MODEL]
        if dec_eng._spec_k:
            assert dec_eng.spec_stats["steps"] > 0
        # zero steady-state recompiles on BOTH engines (CI criterion)
        for svc in dis.workers:
            for name, p in svc.engines[MODEL].perf.state().items():
                assert p["steadyRecompiles"] == 0, (svc.worker_id, name, p)
        # the decode admission really was warm (imported pages matched)
        assert dec_eng.alloc.hits > 0


async def test_sampled_stream_with_seed_identical():
    """Seeded sampled streams survive migration bit-for-bit too: the
    seed resolves per-request, so the decode worker draws the exact same
    sampler chain the unified worker would."""
    opts = dict(temperature=0.9, seed=1234)
    async with Fleet(["unified"]) as uni:
        text_u, _ = await uni.run(n=12, **opts)
    async with Fleet(["prefill", "decode"]) as dis:
        text_d, res = await dis.run(n=12, **opts)
    assert text_d == text_u and res.workerId.startswith("w-decode")


async def test_prefill_only_fleet_serves_locally_with_counted_fallback():
    """No decode pool → no disagg plan; whole-request placement refuses
    cross-role scoring but substitutes the prefill pool explicitly
    (counted) so the fleet serves instead of wedging."""
    async with Fleet(["prefill"]) as f:
        text, res = await f.run()
        assert text
        assert res.workerId.startswith("w-prefill")
        assert f.disagg_count("planned") == 0
        assert f.disagg_count("cross_role") >= 1


async def test_transfer_failure_falls_back_to_local_serving():
    """A failing import NACKs the migration; the prefill worker serves
    the request locally and the stream still matches unified output."""
    async with Fleet(["unified"]) as uni:
        text_u, _ = await uni.run()
    async with Fleet(["prefill", "decode"]) as dis:
        dec_eng = dis.workers[1].engines[MODEL]

        def boom(*_a, **_k):
            raise RuntimeError("injected import failure")

        dec_eng.import_prefix_pages = boom  # type: ignore[method-assign]
        text_d, res = await dis.run()
        assert text_d == text_u
        assert res.workerId.startswith("w-prefill")
        assert dis.disagg_count("planned") == 1
        assert dis.disagg_count("handoff") == 0
        assert dis.disagg_count("fallback") == 1


async def test_decode_worker_at_capacity_nacks_handoff_job():
    """The decode-phase assignment NACKs like any other over-capacity
    assignment; the requeue replans from scratch (stale plan stripped)."""
    async with Fleet(["prefill", "decode"]) as dis:
        # decode worker claims to be saturated AFTER planning: force its
        # capacity to zero so the handoff assignment NACKs
        dec = dis.workers[1]
        dec.max_concurrent = 0
        text, res = await dis.run()
        assert text  # served (locally or after replan) — never lost
        assert res.success
        # the handoff assignment really was refused at least once
        assert int(dis.scheduler._jobs_total.value(event="nacked")) >= 1


async def test_orphan_mid_migration_releases_both_sides():
    """Satellite 1: a job that dies mid-migration front-requeues with
    reason migration_lost, after kv_release went to BOTH workers and the
    stale plan was stripped from the request metadata."""
    bus = InMemoryBus()
    await bus.connect()
    cfg = fleet_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    released: list[tuple[str, str]] = []

    async def on_p(_ch, raw):
        m = json.loads(raw)
        if m.get("type") == "kv_release":
            released.append(("p1", m["jobId"]))

    async def on_d(_ch, raw):
        m = json.loads(raw)
        if m.get("type") == "kv_release":
            released.append(("d1", m["jobId"]))

    await bus.subscribe("worker:p1:job", on_p)
    await bus.subscribe("worker:d1:job", on_d)
    try:
        req = InferenceRequest(
            id="mig-job", model=MODEL, prompt="x",
            metadata={"disagg": {"decodeWorkerId": "d1"}})
        assignment = JobAssignment(jobId="mig-job", workerId="p1",
                                   request=req, timeout=60_000)
        scheduler.active_jobs["mig-job"] = assignment
        scheduler._migrations["mig-job"] = {
            "from": "p1", "to": "d1", "at": time.time()}
        await scheduler._orphan_job(assignment, reason="orphan_sweep")
        await bus.flush()
        assert sorted(released) == [("d1", "mig-job"), ("p1", "mig-job")]
        assert int(scheduler._disagg_total.value(
            event="migration_lost")) == 1
        queued = scheduler.get_job_queue()
        assert [r.id for r in queued] == ["mig-job"]
        assert queued[0].priority == Priority.high
        assert "disagg" not in queued[0].metadata
        assert "disaggPhase" not in queued[0].metadata
        assert "mig-job" not in scheduler._migrations
        # the flight recorder carries the migration_lost event
        from gridllm_tpu.obs import default_flight_recorder

        ring = default_flight_recorder().snapshot()["rings"].get(
            "scheduler", [])
        assert any(e.get("event") == "migration_lost"
                   and e.get("job") == "mig-job" for e in ring)
    finally:
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()


async def test_kv_release_drops_partial_import_state():
    """A kv_release landing mid-assembly drops the receiver's partial
    state (buffers + subscription) and NACKs the ack key — the
    partially-imported-pages half of satellite 1."""
    from gridllm_tpu.transfer import ack_key, kvx_channel, ready_key
    from gridllm_tpu.transfer.wire import build_header, iter_chunks

    import numpy as np

    bus = InMemoryBus()
    await bus.connect()
    eng = make_engine()
    svc = WorkerService(bus, {MODEL: eng},
                        WorkerConfig(worker_id="d1", role="decode"),
                        stream_flush_ms=5)
    await svc.start()
    try:
        k = np.zeros((2, 2, 8, 2, 16), np.float32)
        header, payload = build_header("rel-1", MODEL, list(range(16)), k, k,
                                       chunk_bytes=64)
        await bus.publish("worker:d1:job", json.dumps({
            "type": "kv_import", "jobId": "rel-1", "fromWorker": "p1",
            "header": header}))
        await bus.flush()
        assert await bus.get(ready_key("rel-1")) == "1"
        frames = [f for _s, f in iter_chunks(header, payload)]
        await bus.publish(kvx_channel("rel-1"), frames[0])  # partial
        await bus.flush()
        assert svc.kvx.inflight == 1
        await bus.publish("worker:d1:job", json.dumps({
            "type": "kv_release", "jobId": "rel-1"}))
        await bus.flush()
        assert svc.kvx.inflight == 0
        assert "rel-1" in svc._kvx_aborted
        ack = json.loads(await bus.get(ack_key("rel-1")))
        assert ack["ok"] is False
        # a straggler chunk after release is ignored, never installed
        await bus.publish(kvx_channel("rel-1"), frames[1])
        await bus.flush()
        assert svc.kvx.imported == {}
    finally:
        await svc.stop(announce=False)
        await bus.disconnect()


async def test_registry_roles_and_headroom_from_heartbeats():
    """Satellite 2: role + decode-slot headroom ride heartbeats into the
    registry; _select_worker refuses cross-role placement."""
    async with Fleet(["prefill", "decode"]) as f:
        reg = f.registry
        # heartbeats carried role + headroom
        for _ in range(20):
            ws = reg.get_all_workers()
            if (len(ws) == 2
                    and {w.role for w in ws} == {"prefill", "decode"}):
                break
            await asyncio.sleep(0.1)
        roles = {w.workerId: w.role for w in reg.get_all_workers()}
        assert set(roles.values()) == {"prefill", "decode"}
        dec = next(w for w in reg.get_all_workers() if w.role == "decode")
        assert dec.decodeSlotsFree == 2  # both slots open
        assert dec.httpAddr  # advertised for the HTTP fallback
        req = InferenceRequest(id="sel-1", model=MODEL, prompt="x")
        # role-strict: the prefill pool never serves decode-phase asks
        pre = f.scheduler._select_worker(req, role="prefill")
        assert pre is not None and pre.role == "prefill"
        assert f.scheduler._select_worker(req, role="decode").role == "decode"
        # gridllm_workers_live{role} renders from the same registry
        text = f.scheduler.metrics.render()
        assert 'gridllm_workers_live{role="prefill"} 1' in text
        assert 'gridllm_workers_live{role="decode"} 1' in text


# ------------------------------------------------- two-process smoke (slow)


def _spawn_child(port: int, worker_id: str, role: str) -> subprocess.Popen:
    """Spawn a worker child. NEVER block on its stdout here: the RESP
    broker the child connects to runs on THIS test's event loop, so a
    synchronous readline would deadlock the handshake — readiness is
    observed through the registry instead (like tests/test_chaos.py)."""
    import os

    env = {**os.environ, "PYTHONPATH": str(CHILD.parent.parent)}
    env.pop("XLA_FLAGS", None)
    return subprocess.Popen(
        [sys.executable, str(CHILD), str(port), worker_id, role],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


@pytest.mark.slow
async def test_two_process_fleet_byte_identical_and_fallback_on_kill():
    """disagg-smoke (satellite 5): a two-process prefill+decode fleet
    over a REAL RESP broker serves a greedy stream byte-identical to the
    in-process unified engine; then the decode worker is killed and the
    next request still completes through the prefill worker's local
    fallback (or an orphan-requeue replan) with the same bytes."""
    from gridllm_tpu.bus import create_bus
    from gridllm_tpu.bus.broker import GridBusBroker

    # in-process unified reference through a real WorkerService so the
    # prompt rendering matches the children's exactly
    async with Fleet(["unified"]) as uni:
        text_ref, _ = await uni.run(n=12)

    broker = GridBusBroker()
    await broker.start(port=0)
    url = f"resp://127.0.0.1:{broker.port}"
    pre = dec = None
    bus = create_bus(url)
    await bus.connect()
    cfg = SchedulerConfig(
        worker_heartbeat_timeout_ms=2_000,
        worker_cleanup_interval_ms=200,
        connection_monitor_interval_ms=200,
        quick_disconnect_window_ms=1_000,
        orphan_assign_threshold_ms=500,
        job_timeout_ms=180_000, retry_attempts=2, retry_delay_ms=100,
        sweep_interval_ms=200,
    )
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    try:
        pre = _spawn_child(broker.port, "p1", "prefill")
        dec = _spawn_child(broker.port, "d1", "decode")
        for _ in range(1200):  # engine builds pay first-compile costs
            if len(registry.get_online_workers()) == 2:
                break
            assert pre.poll() is None and dec.poll() is None, \
                "a worker child died during startup"
            await asyncio.sleep(0.1)
        assert len(registry.get_online_workers()) == 2
        # the disagg plan needs the ROLES too, which ride heartbeats
        for _ in range(100):
            roles = {w.role for w in registry.get_online_workers()}
            if roles == {"prefill", "decode"}:
                break
            await asyncio.sleep(0.1)
        assert {w.role for w in registry.get_online_workers()} == \
            {"prefill", "decode"}

        async def run_once(rid: str) -> tuple[str, str]:
            chunks: list[str] = []

            async def on_chunk(c) -> None:
                chunks.append(c.response)

            req = InferenceRequest(
                id=rid, model=MODEL, prompt=PROMPT, stream=True,
                options={"temperature": 0, "num_predict": 12},
                metadata={"requestType": "inference"})
            res = await scheduler.submit_streaming_job(
                req, on_chunk, timeout_ms=150_000)
            assert res.success, res.error
            return "".join(chunks), res.workerId

        text1, wid1 = await run_once("two-proc-1")
        assert text1 == text_ref
        assert wid1 == "d1", f"expected decode worker, got {wid1}"
        assert int(scheduler._disagg_total.value(event="handoff")) == 1

        # kill the decode worker, then submit: whether the death lands
        # before the plan, mid-transfer, or mid-decode, the request must
        # still complete with the same bytes (local fallback on p1, or
        # migration_lost orphan-requeue → replan)
        dec.kill()
        dec.wait(timeout=30)
        text2, wid2 = await run_once("two-proc-2")
        assert text2 == text_ref
        assert wid2 == "p1"
    finally:
        for proc in (pre, dec):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()
        await broker.stop()
