"""Observability subsystem tests (ISSUE 1): metric encoding golden strings,
tracer span stitching over the in-memory bus, gateway /metrics +
/admin/trace integration with a REAL engine worker, and the timeout
chaos assertion (counter increments, no leaked active span)."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from gridllm_tpu.bus.memory import InMemoryBus
from gridllm_tpu.gateway.app import create_app
from gridllm_tpu.obs import MetricsRegistry, Tracer, trace_channel
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.config import Config
from gridllm_tpu.utils.types import InferenceRequest

from .helpers import FakeWorker, fast_config

# ---------------------------------------------------------------------------
# metrics: instruments + Prometheus text encoding
# ---------------------------------------------------------------------------


def test_counter_encoding_golden():
    reg = MetricsRegistry()
    c = reg.counter("http_requests_total", "Total requests.",
                    ("route", "status"))
    c.inc(route="/api/generate", status="200")
    c.inc(2, route="/api/generate", status="200")
    c.inc(route="/v1/models", status="404")
    assert reg.render() == (
        "# HELP http_requests_total Total requests.\n"
        "# TYPE http_requests_total counter\n"
        'http_requests_total{route="/api/generate",status="200"} 3\n'
        'http_requests_total{route="/v1/models",status="404"} 1\n'
    )


def test_gauge_encoding_and_ops():
    reg = MetricsRegistry()
    g = reg.gauge("queue_depth", "Queued jobs.")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value() == 3
    assert reg.render() == (
        "# HELP queue_depth Queued jobs.\n"
        "# TYPE queue_depth gauge\n"
        "queue_depth 3\n"
    )


def test_histogram_bucketing_and_encoding_golden():
    reg = MetricsRegistry()
    h = reg.histogram("latency_seconds", "Latency.", ("op",),
                      buckets=(0.1, 1.0, 5.0))
    for v in (0.05, 0.5, 0.7, 3.0, 99.0):
        h.observe(v, op="gen")
    assert h.count(op="gen") == 5
    assert h.sum(op="gen") == pytest.approx(103.25)
    assert reg.render() == (
        "# HELP latency_seconds Latency.\n"
        "# TYPE latency_seconds histogram\n"
        'latency_seconds_bucket{op="gen",le="0.1"} 1\n'
        'latency_seconds_bucket{op="gen",le="1"} 3\n'
        'latency_seconds_bucket{op="gen",le="5"} 4\n'
        'latency_seconds_bucket{op="gen",le="+Inf"} 5\n'
        'latency_seconds_sum{op="gen"} 103.25\n'
        'latency_seconds_count{op="gen"} 5\n'
    )


def test_label_escaping():
    reg = MetricsRegistry()
    c = reg.counter("weird_total", "Weird labels.", ("msg",))
    c.inc(msg='say "hi"\nback\\slash')
    out = reg.render()
    assert 'msg="say \\"hi\\"\\nback\\\\slash"' in out


def test_get_or_create_idempotent_and_type_safe():
    reg = MetricsRegistry()
    c1 = reg.counter("things_total", "Things.", ("kind",))
    c2 = reg.counter("things_total", "Things.", ("kind",))
    assert c1 is c2
    with pytest.raises(ValueError):
        reg.gauge("things_total", "Things.")
    with pytest.raises(ValueError):
        reg.counter("things_total", "Things.", ("other",))


def test_collector_runs_at_render_and_is_replaceable():
    reg = MetricsRegistry()
    g = reg.gauge("depth", "Depth.")
    reg.add_collector("src", lambda: g.set(7))
    assert "depth 7" in reg.render()
    reg.add_collector("src", lambda: g.set(9))  # latest wins
    assert "depth 9" in reg.render()

    def boom() -> None:
        raise RuntimeError("dead stack")

    reg.add_collector("src", boom)  # a dead collector must not break scrape
    assert "depth 9" in reg.render()


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------


def test_tracer_span_lifecycle_and_leak_free_abort():
    t = Tracer(source="gateway")
    root = t.begin("r1", "gateway.request", model="m1")
    with t.span("r1", "queue.wait"):
        pass
    assert t.active_count() == 1  # root still open
    t.end(root, outcome="success")
    assert t.active_count() == 0
    spans = t.finish("r1")
    assert [s["name"] for s in spans] == ["gateway.request", "queue.wait"]
    assert spans[0]["meta"]["outcome"] == "success"

    # abort closes open spans and marks them
    t2 = Tracer(source="gateway")
    t2.begin("r2", "gateway.request")
    t2.abort("r2", reason="timeout")
    assert t2.active_count() == 0
    spans = t2.export("r2")
    assert spans[0]["meta"]["aborted"] is True
    assert spans[0]["meta"]["reason"] == "timeout"


def test_tracer_lru_eviction():
    t = Tracer(source="gateway", max_traces=2)
    for i in range(4):
        t.event(f"r{i}", "e")
        t.finish(f"r{i}")
    assert t.ids() == ["r2", "r3"]
    assert t.export("r0") is None


def test_histogram_bucket_mismatch_raises():
    reg = MetricsRegistry()
    h = reg.histogram("occ", "Occ.", buckets=(1.0, 2.0))
    assert reg.histogram("occ", "Occ.", buckets=(2.0, 1.0)) is h  # same set
    with pytest.raises(ValueError):
        reg.histogram("occ", "Occ.", buckets=(1.0, 3.0))


def test_tracer_post_seal_spans_fold_into_done():
    """Spans recorded after finish() (a retry event landing once the waiter
    timed out and sealed the trace) must join the finished timeline, not
    strand in the unsealed buffer forever."""
    t = Tracer(source="gateway")
    t.event("r1", "a")
    t.finish("r1")
    t.event("r1", "scheduler.retry")
    assert [s["name"] for s in t.export("r1")] == ["a", "scheduler.retry"]
    assert not t._closed
    # a queue span opened+ended after the seal folds the same way
    s = t.begin("r1", "queue.wait")
    t.end(s)
    assert not t._closed and t.active_count() == 0
    assert [s["name"] for s in t.export("r1")] == [
        "a", "scheduler.retry", "queue.wait"]


def test_tracer_closed_buffer_hard_cap():
    """Requests that never reach a terminal seal cannot grow the unsealed
    buffer without bound — overflow force-seals oldest-first."""
    t = Tracer(source="gateway", max_traces=2)
    for i in range(5):
        t.event(f"r{i}", "e")
    assert len(t._closed) == 2
    assert t.ids() == ["r1", "r2"]  # r0 force-sealed then LRU-evicted


def test_tracer_late_end_metadata_survives_seal_race():
    """The scheduler's failure handler aborts the trace before the waiter's
    finally ends the root span — the waiter's outcome must land anyway."""
    t = Tracer(source="gateway")
    root = t.begin("r1", "gateway.request")
    t.abort("r1", reason="failed")
    t.end(root, outcome="failed")
    spans = t.export("r1")
    assert spans[0]["meta"]["outcome"] == "failed"
    assert "aborted" not in spans[0]["meta"]
    assert t.active_count() == 0


def test_tracer_ingest_replaces_same_source():
    """A re-publication (full timeline each time) replaces that source's
    spans instead of duplicating them; other sources are untouched."""
    t = Tracer(source="gateway")
    t.ingest("r1", [
        {"name": "worker.nack", "source": "worker:w1", "start": 1.0, "end": 1.0},
    ])
    t.ingest("r1", [
        {"name": "worker.nack", "source": "worker:w1", "start": 1.0, "end": 1.0},
        {"name": "worker.execute", "source": "worker:w1", "start": 2.0, "end": 3.0},
    ])
    assert [s["name"] for s in t.export("r1")] == [
        "worker.nack", "worker.execute"]
    t.ingest("r1", [
        {"name": "worker.execute", "source": "worker:w2", "start": 4.0, "end": 5.0},
    ])
    assert [s["source"] for s in t.export("r1")] == [
        "worker:w1", "worker:w1", "worker:w2"]


def test_tracer_eviction_never_drops_inflight_request():
    """ISSUE 2 tracer hygiene: LRU pressure on the finished store must not
    evict a request that still has OPEN gateway spans — its already-ingested
    worker half would vanish and finish() would later re-insert only the
    gateway half (a half-merged timeline)."""
    t = Tracer(source="gateway", max_traces=2)
    t.begin("live", "gateway.request")  # in flight gateway-side
    t.ingest("live", [
        {"name": "worker.execute", "source": "worker:w1",
         "start": 1.0, "end": 2.0},
    ])
    # flood the LRU with finished traces — "live" must survive the trims
    for i in range(5):
        t.event(f"r{i}", "e")
        t.finish(f"r{i}")
    assert "live" in t.ids()
    t.finish("live")
    names = {s["name"] for s in t.export("live")}
    assert names == {"gateway.request", "worker.execute"}  # both halves


def test_tracer_ingest_seals_open_remote_spans():
    """A publication carrying OPEN spans (a dying worker's dump — sealed
    publications never have them) must not leave remote spans dangling open
    forever in the stitched view."""
    t = Tracer(source="gateway")
    t.ingest("r1", [
        {"name": "worker.execute", "source": "worker:w1",
         "start": 5.0, "end": None},
    ])
    span = t.export("r1")[0]
    assert span["end"] is not None
    assert span["meta"]["aborted"] is True
    assert span["meta"]["reason"] == "unsealed_at_publish"


async def test_orphan_marks_worker_lost_on_trace():
    """When a worker dies mid-request the dead worker never publishes its
    half of the timeline; the orphan path must say so on the trace instead
    of leaving an unexplained gap (ISSUE 2 tracer hygiene)."""
    bus = InMemoryBus(key_prefix="G:")
    await bus.connect()
    cfg = fast_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    w = FakeWorker(bus, "w1", ["m1"], delay_s=30, heartbeat_interval_s=0.1)
    await w.start()
    await bus.flush()

    req = InferenceRequest(id="dead-worker-job", model="m1", prompt="x")
    await scheduler.add_job(req)
    for _ in range(100):  # event-driven dispatch runs as its own task
        await asyncio.sleep(0.02)
        if "dead-worker-job" in scheduler.active_jobs:
            break
    assert "dead-worker-job" in scheduler.active_jobs
    await w.die()  # abrupt: heartbeat key deleted, no unregister
    for _ in range(100):
        await asyncio.sleep(0.05)
        if scheduler.get_stats()["totalJobsOrphaned"]:
            break
    assert scheduler.get_stats()["totalJobsOrphaned"] == 1
    spans = scheduler.tracer.export("dead-worker-job")
    lost = [s for s in spans if s["name"] == "scheduler.worker_lost"]
    assert lost and lost[0]["meta"]["worker"] == "w1"

    await scheduler.shutdown()
    await registry.shutdown()
    await bus.disconnect()


async def test_span_stitching_across_in_memory_bus():
    """Worker-side tracer publishes on trace:{id}; the scheduler's psubscribe
    ingests it into the gateway tracer → one merged timeline."""
    bus = InMemoryBus(key_prefix="G:")
    await bus.connect()
    cfg = fast_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()

    # gateway-side spans for a request
    root = scheduler.tracer.begin("req-1", "gateway.request")
    scheduler.tracer.end(root)
    scheduler.tracer.finish("req-1")

    # worker-side tracer on the other end of the bus
    wt = Tracer(source="worker:w9")
    with wt.span("req-1", "worker.execute", model="m1"):
        wt.event("req-1", "worker.first_token")
    spans = wt.finish("req-1")
    await bus.publish(trace_channel("req-1"), json.dumps(
        {"requestId": "req-1", "workerId": "w9", "spans": spans}))
    await bus.flush()

    timeline = scheduler.tracer.export("req-1")
    names = [s["name"] for s in timeline]
    sources = {s["source"] for s in timeline}
    assert "gateway.request" in names
    assert "worker.execute" in names and "worker.first_token" in names
    assert sources == {"gateway", "worker:w9"}
    # chronological order
    starts = [s["start"] for s in timeline]
    assert starts == sorted(starts)

    await scheduler.shutdown()
    await registry.shutdown()
    await bus.disconnect()


# ---------------------------------------------------------------------------
# gateway integration: /metrics + /admin/trace with the stub worker
# ---------------------------------------------------------------------------


async def _make_stack():
    bus = InMemoryBus(key_prefix="G:")
    await bus.connect()
    cfg = fast_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    app = create_app(bus, registry, scheduler, Config(scheduler=cfg))
    client = TestClient(TestServer(app))
    await client.start_server()
    return client, bus, registry, scheduler


async def _teardown(client, bus, registry, scheduler, *workers):
    for w in workers:
        await w.stop(announce=False)
    await client.close()
    await scheduler.shutdown()
    await registry.shutdown()
    await bus.disconnect()


class TracingFakeWorker(FakeWorker):
    """FakeWorker that also publishes worker-side spans, like WorkerService."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.tracer = Tracer(source=f"worker:{self.worker_id}")

    async def _execute(self, assignment):
        span = self.tracer.begin(assignment.jobId, "worker.execute",
                                 worker=self.worker_id,
                                 model=assignment.request.model)
        try:
            await super()._execute(assignment)
        finally:
            self.tracer.end(span)
            spans = self.tracer.finish(assignment.jobId)
            await self.bus.publish(trace_channel(assignment.jobId), json.dumps({
                "requestId": assignment.jobId,
                "workerId": self.worker_id,
                "spans": spans,
            }))


async def test_gateway_metrics_and_trace_after_completed_request():
    client, bus, registry, scheduler = await _make_stack()
    w = TracingFakeWorker(bus, "w1", ["m1"], stream_tokens=["a", "b", "c"])
    await w.start()
    await bus.flush()

    # streaming request → TTFT observed from the first stream frame
    resp = await client.post("/ollama/api/generate",
                             json={"model": "m1", "prompt": "go"})
    assert resp.status == 200
    await resp.text()
    await bus.flush()

    resp = await client.get("/metrics")
    assert resp.status == 200
    assert "text/plain" in resp.headers["Content-Type"]
    text = await resp.text()

    # request counters labeled by route/status
    assert ('gridllm_gateway_requests_total{route="/ollama/api/generate",'
            'method="POST",status="200"} 1') in text
    # TTFT histogram non-empty
    assert 'gridllm_request_ttft_seconds_count{model="m1"} 1' in text
    # scheduler lifecycle counters + queue gauge
    assert 'gridllm_scheduler_jobs_total{event="completed"} 1' in text
    assert 'gridllm_scheduler_jobs_total{event="dispatched"} 1' in text
    assert "gridllm_scheduler_queue_depth 0" in text
    assert 'gridllm_scheduler_worker_assignments_total{worker="w1"} 1' in text
    # worker liveness gauge (registry collector; no redundant "total"
    # series — sum(gridllm_workers) must equal the fleet size)
    assert 'gridllm_workers{status="online"} 1' in text
    assert 'gridllm_workers{status="total"}' not in text
    # queue-wait histogram recorded
    assert "gridllm_scheduler_queue_wait_seconds_count 1" in text
    # bus counters (process-global registry, concatenated into the scrape)
    assert "gridllm_bus_messages_published_total" in text

    # health snapshots read the SAME counters (satellite: cannot disagree)
    stats = (await (await client.get("/health/jobs")).json())["stats"]
    assert stats["totalJobsProcessed"] == 1
    assert stats["totalJobsCompleted"] == 1
    assert stats["totalJobsTimedOut"] == 0

    # stitched gateway+worker trace
    ids = scheduler.tracer.ids()
    assert len(ids) == 1
    resp = await client.get(f"/admin/trace/{ids[0]}")
    assert resp.status == 200
    body = await resp.json()
    names = [s["name"] for s in body["spans"]]
    assert "gateway.request" in names
    assert "queue.wait" in names
    assert "scheduler.dispatch" in names
    assert "gateway.first_token" in names
    assert "worker.execute" in names
    assert set(body["sources"]) == {"gateway", "worker:w1"}

    # unknown id → 404 envelope
    resp = await client.get("/admin/trace/nope")
    assert resp.status == 404

    await _teardown(client, bus, registry, scheduler, w)


async def test_request_latency_histogram_by_route():
    client, bus, registry, scheduler = await _make_stack()
    for _ in range(3):
        assert (await client.get("/health")).status == 200
    text = await (await client.get("/metrics")).text()
    assert ('gridllm_gateway_request_duration_seconds_count'
            '{route="/health"} 3') in text
    # unmatched paths collapse into one label value (bounded cardinality)
    await client.get("/definitely/not/a/route")
    text = await (await client.get("/metrics")).text()
    assert ('gridllm_gateway_requests_total{route="unmatched",'
            'method="GET",status="404"} 1') in text
    await _teardown(client, bus, registry, scheduler)


# ---------------------------------------------------------------------------
# chaos: timeouts increment the counter and leak no active span
# ---------------------------------------------------------------------------


async def test_timeout_increments_counter_and_leaks_no_span():
    client, bus, registry, scheduler = await _make_stack()
    # worker that sits on the job far past the submit timeout
    w = FakeWorker(bus, "w1", ["m1"], delay_s=30)
    await w.start()
    await bus.flush()

    from gridllm_tpu.scheduler.scheduler import JobTimeoutError

    req = InferenceRequest(id="job-timeout-1", model="m1", prompt="x")
    with pytest.raises(JobTimeoutError):
        await scheduler.submit_and_wait(req, timeout_ms=200)
    await bus.flush()

    stats = scheduler.get_stats()
    assert stats["totalJobsTimedOut"] == 1
    assert stats["totalJobsFailed"] == 1  # timeouts count as failures
    assert stats["totalJobsProcessed"] == 0
    text = scheduler.metrics.render()
    assert 'gridllm_scheduler_jobs_total{event="timeout"} 1' in text
    # no leaked active span anywhere (root + queue spans all sealed)
    assert scheduler.tracer.active_count() == 0, scheduler.tracer.active_ids()
    # the trace survives, marked aborted
    spans = scheduler.tracer.export("job-timeout-1")
    assert spans is not None
    root = next(s for s in spans if s["name"] == "gateway.request")
    assert root["meta"]["outcome"] == "timeout"

    await _teardown(client, bus, registry, scheduler, w)


async def test_server_side_timeout_timer_path():
    """The armed per-job timer (not the waiter) also counts + cleans up."""
    client, bus, registry, scheduler = await _make_stack()
    w = FakeWorker(bus, "w1", ["m1"], delay_s=30)
    await w.start()
    await bus.flush()

    req = InferenceRequest(id="job-timer-1", model="m1", prompt="x",
                           timeout=150)
    await scheduler.add_job(req)
    for _ in range(60):
        await asyncio.sleep(0.05)
        if scheduler.get_stats()["totalJobsTimedOut"]:
            break
    assert scheduler.get_stats()["totalJobsTimedOut"] == 1
    assert scheduler.tracer.active_count() == 0
    # the counter increments before the cancellation publish is delivered —
    # drain the bus so the worker has seen it
    await bus.flush()
    assert w.cancelled == ["job-timer-1"]  # worker told to drop it

    await _teardown(client, bus, registry, scheduler, w)


async def test_worker_removal_counter():
    client, bus, registry, scheduler = await _make_stack()
    w = FakeWorker(bus, "w1", ["m1"])
    await w.start()
    await bus.flush()
    await w.stop()  # announces unregistered
    await bus.flush()
    text = scheduler.metrics.render()
    assert ('gridllm_workers_removed_total{reason="unregistered"} 1'
            in text)
    assert 'gridllm_workers{status="online"} 0' in text
    await _teardown(client, bus, registry, scheduler)
