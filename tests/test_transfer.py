"""KV wire-format and engine export/import units (ISSUE 7 satellite).

Round-trips across page-boundary straddles, unpadded vs lane-padded
pools, int8-quant engines, and refcount safety when an imported prefix
overlaps already-cached pages (no double-free, pinned pages stay
pinned)."""

from __future__ import annotations

import json

import numpy as np
import pytest

from gridllm_tpu.engine import EngineConfig, InferenceEngine
from gridllm_tpu.engine.engine import GenerationRequest
from gridllm_tpu.transfer import (
    Assembler,
    WireError,
    build_header,
    iter_chunks,
)

PS = 8  # page size used throughout


def make_engine(**kw) -> InferenceEngine:
    cfg = dict(
        model="tiny-llama", max_slots=2, page_size=PS, num_pages=64,
        max_pages_per_slot=16, prefill_buckets=(16, 64, 128), seed=42,
        prefill_chunk=16,
    )
    cfg.update(kw)
    return InferenceEngine(EngineConfig(**cfg))


def greedy(engine, rid, prompt, n=12, export_only=False, **opts):
    return engine.generate(GenerationRequest(
        id=rid, prompt=prompt,
        options={"temperature": 0, "num_predict": n, **opts},
        export_only=export_only,
    ))


def roundtrip(header, payload, chunked=True):
    asm = Assembler(header)
    if chunked:
        for _seq, frame in iter_chunks(header, payload):
            asm.feed(frame)
    else:
        asm.feed_raw(payload)
    return asm.arrays()


def migrate(src: InferenceEngine, dst: InferenceEngine, prompt: str,
            chunked=True, chunk_bytes=512) -> int:
    """Export prompt's cached prefix from src, wire round-trip, import
    into dst. Returns imported token count."""
    ids = src.tokenizer.encode(prompt, add_bos=True)
    export = src.export_prefix_pages(ids)
    assert export is not None
    header, payload = build_header(
        "t1", "tiny-llama", export["tokens"], export["k"], export["v"],
        kv_layout=export["kvLayout"], quant=export["quant"],
        chunk_bytes=chunk_bytes)
    tokens, k, v = roundtrip(header, payload, chunked=chunked)
    assert tokens == export["tokens"]
    return dst.import_prefix_pages(tokens, k, v, header)


# ---------------------------------------------------------------- wire units


class TestWireFormat:
    def _hp(self, n_pages=3, chunk_bytes=64):
        rng = np.random.default_rng(0)
        k = rng.standard_normal((2, n_pages, PS, 2, 16)).astype(np.float32)
        v = rng.standard_normal((2, n_pages, PS, 2, 16)).astype(np.float32)
        tokens = list(range(n_pages * PS))
        header, payload = build_header("r1", "m", tokens, k, v,
                                       chunk_bytes=chunk_bytes)
        return header, payload, k, v

    def test_roundtrip_chunked(self):
        header, payload, k, v = self._hp()
        tokens, k2, v2 = roundtrip(header, payload)
        assert tokens == list(range(3 * PS))
        np.testing.assert_array_equal(k, k2)
        np.testing.assert_array_equal(v, v2)

    def test_roundtrip_http_raw(self):
        header, payload, k, v = self._hp()
        _t, k2, v2 = roundtrip(header, payload, chunked=False)
        np.testing.assert_array_equal(k, k2)
        np.testing.assert_array_equal(v, v2)

    def test_duplicate_and_out_of_order_chunks(self):
        header, payload, k, _v = self._hp(chunk_bytes=100)
        frames = [f for _s, f in iter_chunks(header, payload)]
        asm = Assembler(header)
        for f in reversed(frames):  # out of order
            asm.feed(f)
        for f in frames:            # duplicates ignored
            asm.feed(f)
        _t, k2, _v2 = asm.arrays()
        np.testing.assert_array_equal(k, k2)

    def test_crc_mismatch_raises(self):
        header, payload, *_ = self._hp(chunk_bytes=100)
        frames = [f for _s, f in iter_chunks(header, payload)]
        rec = json.loads(frames[1])
        rec["crc"] = (rec["crc"] + 1) & 0xFFFFFFFF
        asm = Assembler(header)
        with pytest.raises(WireError, match="crc"):
            asm.feed(json.dumps(rec))

    def test_digest_mismatch_raises(self):
        header, payload, *_ = self._hp()
        asm = Assembler(header)
        asm.feed_raw(payload[:-4] + b"\x00\x00\x00\x00")
        with pytest.raises(WireError):
            asm.arrays()

    def test_incomplete_raises(self):
        header, payload, *_ = self._hp(chunk_bytes=100)
        asm = Assembler(header)
        asm.feed(next(iter_chunks(header, payload))[1])
        assert not asm.complete
        with pytest.raises(WireError, match="incomplete"):
            asm.payload()

    def test_contiguous_progress(self):
        header, payload, *_ = self._hp(chunk_bytes=50)
        frames = list(iter_chunks(header, payload))
        asm = Assembler(header)
        asm.feed(frames[2][1])
        assert asm.contiguous == 0  # gap at 0
        asm.feed(frames[0][1])
        assert asm.contiguous == 1
        asm.feed(frames[1][1])
        assert asm.contiguous == 3

    def test_bad_version_rejected(self):
        header, _p, *_ = self._hp()
        header["v"] = 99
        with pytest.raises(WireError, match="version"):
            Assembler(header)


# ------------------------------------------------------ engine export/import


@pytest.fixture(scope="module")
def engines():
    """One source + one destination engine shared by the round-trip
    cases (module-scoped: tiny-model compiles dominate test wall time)."""
    return make_engine(), make_engine()


class TestEngineRoundTrip:
    @pytest.mark.parametrize("extra", [0, 1, PS - 1, PS])
    def test_page_boundary_straddles(self, engines, extra):
        """Prompts landing exactly on / one past / one short of a page
        boundary all export the full pages strictly below len-1 and
        reproduce the unified greedy stream on the import side."""
        src, dst = engines
        base = "straddle test of the quick brown fox "
        prompt = (base * 8)[: 5 * 7 + extra]  # vary length around pages
        rid = f"pb-{extra}"
        r_uni = greedy(make_engine(), rid + "-u", prompt)
        r_exp = greedy(src, rid + "-e", prompt, export_only=True)
        assert r_exp.done_reason == "export"
        ids = r_exp.context[:-1]
        export = src.export_prefix_pages(ids)
        assert export is not None
        # coverage = full pages strictly below the last prompt token
        assert len(export["tokens"]) == ((len(ids) - 1) // PS) * PS
        header, payload = build_header(
            rid, "tiny-llama", export["tokens"], export["k"], export["v"])
        tokens, k, v = roundtrip(header, payload)
        n = dst.import_prefix_pages(tokens, k, v, header)
        assert n == len(export["tokens"])
        r_mig = greedy(dst, rid + "-d", prompt)
        assert r_mig.token_ids == r_uni.token_ids
        assert r_mig.cached_tokens == n

    def test_lane_padded_pool_roundtrip(self, monkeypatch):
        """A lane-padded destination pool (kernel-path d<128 models)
        accepts the UNPADDED wire data — import re-pads the lanes; the
        decode stream still matches an unpadded engine's."""
        prompt = "lane padded pool migration check " * 3
        r_uni = greedy(make_engine(), "lp-u", prompt)
        src = make_engine()
        greedy(src, "lp-e", prompt, export_only=True)
        ids = src.tokenizer.encode(prompt, add_bos=True)
        export = src.export_prefix_pages(ids)
        d = export["k"].shape[-1]
        monkeypatch.setattr(InferenceEngine, "_pool_head_dim",
                            lambda self: 128)
        dst = make_engine()
        assert dst.cache.k.shape[-1] == 128 > d  # really padded
        header, payload = build_header(
            "lp", "tiny-llama", export["tokens"], export["k"], export["v"])
        tokens, k, v = roundtrip(header, payload)
        n = dst.import_prefix_pages(tokens, k, v, header)
        assert n == len(tokens)
        # padded lanes beyond d must be zero (the write kernels' contract)
        import jax.numpy as jnp

        pad_region = np.asarray(dst.cache.k[..., d:], dtype=jnp.float32)
        assert float(np.abs(pad_region).max()) == 0.0
        r_mig = greedy(dst, "lp-d", prompt)
        assert r_mig.token_ids == r_uni.token_ids

    def test_int8_quant_engine_roundtrip(self):
        """Weight-only int8 engines migrate KV like any other — the pool
        dtype is the engine dtype, quant rides the header as metadata."""
        q = dict(quantize="int8")
        r_uni = greedy(make_engine(**q), "q-u", "int8 quant migration " * 4)
        src, dst = make_engine(**q), make_engine(**q)
        prompt = "int8 quant migration " * 4
        greedy(src, "q-e", prompt, export_only=True)
        ids = src.tokenizer.encode(prompt, add_bos=True)
        export = src.export_prefix_pages(ids)
        assert export["quant"] == "int8"
        n = migrate(src, dst, prompt)
        assert n > 0
        r_mig = greedy(dst, "q-d", prompt)
        assert r_mig.token_ids == r_uni.token_ids

    def test_dtype_mismatch_rejected(self, engines):
        src, _dst = engines
        prompt = "dtype mismatch check " * 4
        greedy(src, "dm-e", prompt, export_only=True)
        ids = src.tokenizer.encode(prompt, add_bos=True)
        export = src.export_prefix_pages(ids)
        header, payload = build_header(
            "dm", "tiny-llama", export["tokens"],
            export["k"].astype(np.float32), export["v"].astype(np.float32))
        tokens, k, v = roundtrip(header, payload)
        dst = make_engine()
        with pytest.raises(ValueError, match="dtype"):
            dst.import_prefix_pages(tokens, k, v, header)

    def test_geometry_mismatch_rejected(self, engines):
        src, _dst = engines
        prompt = "geometry mismatch check " * 4
        greedy(src, "gm-e", prompt, export_only=True)
        ids = src.tokenizer.encode(prompt, add_bos=True)
        export = src.export_prefix_pages(ids)
        header, payload = build_header(
            "gm", "tiny-llama", export["tokens"], export["k"], export["v"])
        tokens, k, v = roundtrip(header, payload)
        dst = make_engine(page_size=16, prefill_chunk=16)
        with pytest.raises(ValueError, match="page-size"):
            dst.import_prefix_pages(tokens, k, v, header)


class TestRefcountSafety:
    def test_overlap_import_no_double_free_pinned_stays_pinned(self):
        """Importing a prefix that overlaps already-cached pages must not
        install duplicates, must leave live pins untouched, and must keep
        the allocator's page accounting exact (no page ever appears in
        two ownership states — the no-double-free invariant)."""
        prompt = "overlap import refcount safety check " * 3
        src, dst = make_engine(), make_engine()
        # dst already served (and cached) the same prompt
        greedy(dst, "ov-warm", prompt)
        alloc = dst.alloc
        ids = dst.tokenizer.encode(prompt, add_bos=True)
        pinned, _tok = alloc.pin_prefix(ids)
        assert pinned, "prompt pages should be cached on dst"
        refs_before = {p: alloc._refs.get(p) for p in pinned}
        free_before = alloc.free_pages
        cached_before = alloc.cached_pages

        greedy(src, "ov-e", prompt, export_only=True)
        n = migrate(src, dst, prompt)
        assert n > 0
        # every imported page overlapped the existing cache: nothing new
        # was installed, nothing was freed twice
        assert alloc.free_pages == free_before
        assert alloc.cached_pages == cached_before
        for p in pinned:  # live pins untouched by the overlap import
            assert alloc._refs.get(p) == refs_before[p]
        alloc.unpin_pages(pinned)
        # full accounting: free + cached + live-referenced == num_pages
        used = dst.config.num_pages - alloc.free_pages - alloc.cached_pages
        assert used == 0
        assert sorted(set(alloc._free)) == sorted(alloc._free), \
            "duplicate page in the free list (double free)"

    def test_partial_overlap_installs_only_missing_tail(self):
        prompt = "partial overlap only missing tail pages install " * 2
        src = make_engine()
        greedy(src, "po-e", prompt, export_only=True)
        ids = src.tokenizer.encode(prompt, add_bos=True)
        export = src.export_prefix_pages(ids)
        n_pages = len(export["tokens"]) // PS
        assert n_pages >= 2
        dst = make_engine()
        header, payload = build_header(
            "po", "tiny-llama", export["tokens"], export["k"], export["v"])
        tokens, k, v = roundtrip(header, payload)
        # first import only the first page's worth
        h1 = dict(header)
        h1["tokens"] = tokens[:PS]
        h1["numPages"] = 1
        n1 = dst.import_prefix_pages(tokens[:PS], k[:, :1], v[:, :1], h1)
        assert n1 == PS
        cached_1 = dst.alloc.cached_pages
        # full import now only adds the missing tail pages
        n2 = dst.import_prefix_pages(tokens, k, v, header)
        assert n2 == len(tokens)
        assert dst.alloc.cached_pages == cached_1 + (n_pages - 1)

    def test_pool_exhaustion_keeps_shorter_prefix(self):
        """A full pool truncates the install instead of failing — the
        shorter contiguous prefix is still valid, and nothing leaks."""
        prompt = "pool exhaustion truncates the imported prefix " * 2
        src = make_engine()
        greedy(src, "px-e", prompt, export_only=True)
        ids = src.tokenizer.encode(prompt, add_bos=True)
        export = src.export_prefix_pages(ids)
        n_pages = len(export["tokens"]) // PS
        assert n_pages >= 3
        # destination pool with room for fewer pages than offered
        dst = make_engine(num_pages=n_pages - 1, max_pages_per_slot=n_pages)
        header, payload = build_header(
            "px", "tiny-llama", export["tokens"], export["k"], export["v"])
        tokens, k, v = roundtrip(header, payload)
        n = dst.import_prefix_pages(tokens, k, v, header)
        assert n == (n_pages - 1) * PS
        assert dst.alloc.free_pages == 0
        assert dst.alloc.cached_pages == n_pages - 1
