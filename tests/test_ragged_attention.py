"""Ragged paged attention (ISSUE 6): the unified kernel/dispatcher that
serves chunked prefill, decode, and spec-verify in one launch.

Four layers of pinning:

- differential: the ragged jnp reference is BIT-identical to the legacy
  per-phase references (it delegates to them region-by-region), and the
  interpret-mode kernel matches the reference across mixed batches,
  page-boundary straddles, empty slots, windows, and softcap;
- stream parity: greedy engine token streams are identical ragged-on vs
  ragged-off — concurrent mixed batches, warm prefix-cache replays, and
  the speculative path included; GRIDLLM_RAGGED_ATTN=0 restores the
  legacy dispatchers exactly;
- single launch: the kernel-dispatch counters prove a ragged engine
  compiles ONLY `attention_ragged` programs — no per-phase
  decode/chunk/verify dispatches, no per-slot loop;
- recompile hygiene: varying batch mixes (admissions mid-decode, spec
  verify, warm cache) trigger zero steady-state recompiles.
"""

import os
from contextlib import contextmanager

import jax.numpy as jnp
import numpy as np
import pytest

from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine
from gridllm_tpu.obs import default_registry
from gridllm_tpu.obs.perf import recompile_totals
from gridllm_tpu.ops import attention as A
from gridllm_tpu.ops import pallas_kernels as PK

TINY = dict(
    model="tiny-llama",
    max_slots=4,
    page_size=8,
    num_pages=64,
    max_pages_per_slot=8,
    prefill_buckets=(16, 32),
    prefill_chunk=16,
)
# long enough to take the chunked (= ragged mixed-step) admission path
LONG_PROMPT = "ab ab ab ab ab ab ab ab ab ab"
GREEDY = {"temperature": 0.0, "repeat_penalty": 1.0, "num_predict": 24}


@contextmanager
def ragged(flag: bool):
    old = os.environ.get("GRIDLLM_RAGGED_ATTN")
    os.environ["GRIDLLM_RAGGED_ATTN"] = "1" if flag else "0"
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("GRIDLLM_RAGGED_ATTN", None)
        else:
            os.environ["GRIDLLM_RAGGED_ATTN"] = old


def _gen_batch(engine, prompts, opts=GREEDY):
    """Submit all prompts, drive step() until done, return token streams
    in submission order (concurrent batch → mixed steps exercise)."""
    res = {}

    def cb(i):
        def f(_delta, done, r):
            if done:
                res[i] = r

        return f

    for i, p in enumerate(prompts):
        req = GenerationRequest(id=f"r{i}", prompt=p, options=dict(opts))
        req.on_chunk = cb(i)
        engine.submit(req)
    while len(res) < len(prompts):
        engine.step()
    return [res[i] for i in range(len(prompts))]


# ---------------------------------------------------------------------------
# differential: ragged op vs the legacy references / interpret kernel
# ---------------------------------------------------------------------------


def _pools(rng, L=2, P=32, ps=8, kvh=2, d=16):
    kp = jnp.asarray(rng.normal(size=(L, P, ps, kvh, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(L, P, ps, kvh, d)), jnp.float32)
    return kp, vp


def test_ragged_ref_bitwise_equals_legacy_refs():
    """The fallback path delegates region-by-region to the legacy
    references — ragged-on and ragged-off jnp paths are the same bits."""
    rng = np.random.default_rng(0)
    kp, vp = _pools(rng)
    ps, kvh, d, h = 8, 2, 16, 4
    S, maxp, T = 3, 6, 4
    table = jnp.asarray(
        rng.choice(32, size=S * maxp, replace=False).reshape(S, maxp),
        jnp.int32)
    # lengths straddle page boundaries; slot 1 empty (fresh admission)
    lengths = jnp.asarray([13, 0, 37], jnp.int32)
    li = jnp.int32(1)

    q = jnp.asarray(rng.normal(size=(S, h, d)), jnp.float32)
    kc = jnp.asarray(rng.normal(size=(S, kvh, d)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(S, kvh, d)), jnp.float32)
    want = A.paged_attention_decode_ref(
        q, kp[1], vp[1], table, lengths, ps, k_cur=kc, v_cur=vc)
    _, got = A.ragged_paged_attention(
        kp, vp, ps, q_group=q[:, None], page_table=table,
        group_lengths=lengths, k_group=kc[:, None], v_group=vc[:, None],
        layer=li, use_pallas=False)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got[:, 0]))

    qv = jnp.asarray(rng.normal(size=(S, T, h, d)), jnp.float32)
    kcv = jnp.asarray(rng.normal(size=(S, T, kvh, d)), jnp.float32)
    vcv = jnp.asarray(rng.normal(size=(S, T, kvh, d)), jnp.float32)
    wantv = A.paged_attention_verify_ref(
        qv, kp, vp, table, lengths, ps, kcv, vcv, layer=li)
    _, gotv = A.ragged_paged_attention(
        kp, vp, ps, q_group=qv, page_table=table, group_lengths=lengths,
        k_group=kcv, v_group=vcv, layer=li, use_pallas=False)
    np.testing.assert_array_equal(np.asarray(wantv), np.asarray(gotv))

    C = 16
    row, start = table[2], jnp.int32(16)
    qc = jnp.asarray(rng.normal(size=(1, C, h, d)), jnp.float32)
    kcc = jnp.asarray(rng.normal(size=(C, kvh, d)), jnp.float32)
    vcc = jnp.asarray(rng.normal(size=(C, kvh, d)), jnp.float32)
    wantc = A.attention_prefix_chunk(
        qc, kp, vp, row, start, start + C, ps, k_cur=kcc, v_cur=vcc,
        layer=li, use_pallas=False)
    gotc, _ = A.ragged_paged_attention(
        kp, vp, ps, q_chunk=qc, chunk_row=row, chunk_start=start,
        chunk_total=start + C, k_chunk=kcc, v_chunk=vcc, layer=li,
        use_pallas=False)
    np.testing.assert_array_equal(np.asarray(wantc), np.asarray(gotc))


@pytest.mark.parametrize("softcap,window", [(0.0, 0), (30.0, 0), (0.0, 9)])
def test_ragged_kernel_mixed_batch_matches_ref(softcap, window):
    """ONE interpret-mode launch over chunk + decode + verify regions
    matches the per-region references — incl. page straddles, an empty
    slot, a partially filled last page, softcap, and sliding window."""
    rng = np.random.default_rng(1)
    kp, vp = _pools(rng)
    ps, kvh, d, h = 8, 2, 16, 4
    S, maxp, T, C = 3, 6, 4, 16
    table = jnp.asarray(
        rng.choice(26, size=S * maxp, replace=False).reshape(S, maxp),
        jnp.int32)
    lengths = jnp.asarray([13, 0, 37], jnp.int32)
    li = jnp.int32(0)
    row = jnp.asarray([26, 27, 28, 29, 30, 31], jnp.int32)
    start = jnp.int32(16)   # page-aligned, mid-prompt chunk
    total = start + jnp.int32(11)  # ragged chunk: only 11 of 16 rows valid

    qv = jnp.asarray(rng.normal(size=(S, T, h, d)), jnp.float32)
    kcv = jnp.asarray(rng.normal(size=(S, T, kvh, d)), jnp.float32)
    vcv = jnp.asarray(rng.normal(size=(S, T, kvh, d)), jnp.float32)
    qc = jnp.asarray(rng.normal(size=(1, C, h, d)), jnp.float32)
    kcc = jnp.asarray(rng.normal(size=(C, kvh, d)), jnp.float32)
    vcc = jnp.asarray(rng.normal(size=(C, kvh, d)), jnp.float32)

    wantv = A.paged_attention_verify_ref(
        qv, kp, vp, table, lengths, ps, kcv, vcv, layer=li,
        logit_softcap=softcap, window=window)
    wantc = A._prefix_chunk_ref(
        qc, kp, vp, row, start, total, ps, k_cur=kcc, v_cur=vcc, layer=li,
        logit_softcap=softcap, window=window)

    gc, gg = PK.ragged_attention(
        kp, vp, ps, q_chunk=qc, chunk_row=row, chunk_start=start,
        chunk_total=total, k_chunk=kcc, v_chunk=vcc,
        q_group=qv, page_table=table, group_lengths=lengths,
        k_group=kcv, v_group=vcv, layer=li, interpret=True,
        softcap=softcap, window=window)
    np.testing.assert_allclose(
        np.asarray(gc), np.asarray(wantc), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(gg), np.asarray(wantv), rtol=2e-5, atol=2e-5)


def test_ragged_kernel_group_only_and_chunk_only():
    """Region-absent variants (pure decode step / pure chunk) run the
    same kernel with the other region compiled out."""
    rng = np.random.default_rng(2)
    kp, vp = _pools(rng)
    ps, kvh, d, h = 8, 2, 16, 4
    S, maxp = 3, 6
    table = jnp.asarray(
        rng.choice(32, size=S * maxp, replace=False).reshape(S, maxp),
        jnp.int32)
    lengths = jnp.asarray([7, 25, 1], jnp.int32)
    li = jnp.int32(1)

    q = jnp.asarray(rng.normal(size=(S, 1, h, d)), jnp.float32)
    kc = jnp.asarray(rng.normal(size=(S, 1, kvh, d)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(S, 1, kvh, d)), jnp.float32)
    want = A.paged_attention_decode_ref(
        q[:, 0], kp[1], vp[1], table, lengths, ps,
        k_cur=kc[:, 0], v_cur=vc[:, 0])
    _, got = PK.ragged_attention(
        kp, vp, ps, q_group=q, page_table=table, group_lengths=lengths,
        k_group=kc, v_group=vc, layer=li, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got[:, 0]), np.asarray(want), rtol=2e-5, atol=2e-5)

    C = 16
    qc = jnp.asarray(rng.normal(size=(1, C, h, d)), jnp.float32)
    kcc = jnp.asarray(rng.normal(size=(C, kvh, d)), jnp.float32)
    vcc = jnp.asarray(rng.normal(size=(C, kvh, d)), jnp.float32)
    row = table[1]
    start = jnp.int32(8)
    wantc = A._prefix_chunk_ref(
        qc, kp, vp, row, start, start + C, ps, k_cur=kcc, v_cur=vcc,
        layer=li)
    gotc, _ = PK.ragged_attention(
        kp, vp, ps, q_chunk=qc, chunk_row=row, chunk_start=start,
        chunk_total=start + C, k_chunk=kcc, v_chunk=vcc, layer=li,
        interpret=True)
    np.testing.assert_allclose(
        np.asarray(gotc), np.asarray(wantc), rtol=2e-5, atol=2e-5)


def test_ragged_kernel_first_chunk_empty_prefix():
    """start == 0 (a fresh prompt's first chunk): no prefix pages are
    streamed, causal attention over the chunk alone."""
    rng = np.random.default_rng(3)
    kp, vp = _pools(rng)
    ps, kvh, d, h = 8, 2, 16, 4
    C = 16
    row = jnp.asarray([0, 1, 2, 3, 4, 5], jnp.int32)
    qc = jnp.asarray(rng.normal(size=(1, C, h, d)), jnp.float32)
    kcc = jnp.asarray(rng.normal(size=(C, kvh, d)), jnp.float32)
    vcc = jnp.asarray(rng.normal(size=(C, kvh, d)), jnp.float32)
    want = A._prefix_chunk_ref(
        qc, kp, vp, row, jnp.int32(0), jnp.int32(C), ps,
        k_cur=kcc, v_cur=vcc)
    got, _ = PK.ragged_attention(
        kp, vp, ps, q_chunk=qc, chunk_row=row, chunk_start=jnp.int32(0),
        chunk_total=jnp.int32(C), k_chunk=kcc, v_chunk=vcc, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# greedy stream parity: ragged-on vs ragged-off engines
# ---------------------------------------------------------------------------


def _engine(ragged_on: bool, **kw):
    with ragged(ragged_on):
        return InferenceEngine(EngineConfig(**TINY, **kw))


def test_greedy_parity_concurrent_mixed_batch():
    """Long (chunked → ragged mixed-step) and short (bucketed) prompts in
    one concurrent batch: identical greedy streams ragged-on vs off."""
    prompts = [LONG_PROMPT, "hello", LONG_PROMPT + " xyz", "q"]
    off = _engine(False, spec_decode=False, prefix_cache=False)
    with ragged(False):
        want = [r.token_ids for r in _gen_batch(off, prompts)]
    on = _engine(True, spec_decode=False, prefix_cache=False)
    with ragged(True):
        got = [r.token_ids for r in _gen_batch(on, prompts)]
    assert got == want
    assert all(len(t) == GREEDY["num_predict"] for t in got)


def test_greedy_parity_warm_prefix_cache():
    """Warm (cache-hit) admissions replay through the ragged mixed path
    bit-identically: cold == warm == legacy."""
    off = _engine(False, spec_decode=False)
    with ragged(False):
        want = [_gen_batch(off, [LONG_PROMPT])[0].token_ids
                for _ in range(2)]
    on = _engine(True, spec_decode=False)
    with ragged(True):
        got = [_gen_batch(on, [LONG_PROMPT])[0].token_ids
               for _ in range(2)]
    assert got == want
    assert got[0] == got[1]            # cold == warm
    assert on.alloc.hits > 0           # the warm round really hit


def test_greedy_parity_speculative():
    """Spec-on engines: the ragged verify path (one launch, no per-slot
    loop) keeps greedy streams identical, with real acceptance."""
    prompts = [LONG_PROMPT, "hello"]
    off = _engine(False, spec_decode=True, spec_k=4, prefix_cache=False)
    with ragged(False):
        want = _gen_batch(off, prompts)
    on = _engine(True, spec_decode=True, spec_k=4, prefix_cache=False)
    with ragged(True):
        got = _gen_batch(on, prompts)
    assert [r.token_ids for r in got] == [r.token_ids for r in want]
    assert sum(r.spec_accepted for r in got) > 0


def test_escape_hatch_restores_legacy_dispatchers():
    """GRIDLLM_RAGGED_ATTN=0 engines never trace the ragged op."""
    c = default_registry().get("gridllm_kernel_dispatch_total")

    def count(op):
        return sum(v for labels, v in c.items() if labels["op"] == op)

    before = count("attention_ragged")
    legacy_before = count("attention_decode")
    off = _engine(False, spec_decode=False, prefix_cache=False)
    with ragged(False):
        _gen_batch(off, [LONG_PROMPT])
    assert count("attention_ragged") == before
    assert count("attention_decode") > legacy_before


# ---------------------------------------------------------------------------
# single-launch proof: dispatch counters
# ---------------------------------------------------------------------------


def test_single_attention_dispatch_per_step():
    """A ragged engine serving a mixed workload (chunked admission +
    decode + spec verify + warm cache) compiles ONLY attention_ragged
    programs — the legacy per-phase ops (and verify's per-slot chunk
    loop) are never dispatched. Counters count per compiled program, so
    zero deltas prove the phases share the unified entry point."""
    c = default_registry().get("gridllm_kernel_dispatch_total")

    def snap():
        return {op: sum(v for labels, v in c.items()
                        if labels["op"] == op)
                for op in ("attention_ragged", "attention_decode",
                           "attention_prefix_chunk", "attention_verify")}

    before = snap()
    eng = _engine(True, spec_decode=True, spec_k=4)
    with ragged(True):
        _gen_batch(eng, [LONG_PROMPT, "hello"])
        _gen_batch(eng, [LONG_PROMPT])  # warm-cache replay
    after = snap()
    assert after["attention_ragged"] > before["attention_ragged"]
    for op in ("attention_decode", "attention_prefix_chunk",
               "attention_verify"):
        assert after[op] == before[op], op


# ---------------------------------------------------------------------------
# recompile hygiene: varying batch mixes, zero steady-state recompiles
# ---------------------------------------------------------------------------


def test_zero_steady_recompiles_over_varying_mixes():
    """After the first completed request arms the tripwire, admissions
    mid-decode (mixed steps), different batch fills, spec verify, and
    warm-cache replays must all reuse compiled programs."""
    eng = _engine(True, spec_decode=True, spec_k=4)
    with ragged(True):
        # warm every program this test's mixes need: chunked + bucketed
        # admission, decode, verify, warm-cache window seeding
        _gen_batch(eng, [LONG_PROMPT, "hello"])
        _gen_batch(eng, [LONG_PROMPT])
        assert eng.perf.armed
        steady0 = recompile_totals()["steady"]
        _gen_batch(eng, [LONG_PROMPT, "hi", LONG_PROMPT + " xyz"])
        _gen_batch(eng, ["hello", LONG_PROMPT])
        steady = recompile_totals()["steady"]
    assert steady == steady0, recompile_totals()["byFn"]


def test_ragged_pool_unpadded_and_memory_fields():
    """_pool_head_dim under ragged: the pool stays at the model's head
    dim when KVH*D is flat-lane aligned (no 2x lane-pad bytes), and
    /admin/memory's allocator math reports zero lane-pad overhead with
    kvLayout "ragged". (Interpret/CPU engines keep the unpadded pool
    either way; the layout assertion is on the accounting fields.)"""
    eng = _engine(True, spec_decode=False)
    alloc = eng.memory_arrays()["alloc"]
    assert alloc["kvLayout"] == "ragged"
    assert alloc["lanePadOverheadBytes"] == 0
    assert eng.cache.k.shape[-1] == eng.cfg.head_dim_

    off = _engine(False, spec_decode=False)
    assert off.memory_arrays()["alloc"]["kvLayout"] == "legacy"
