"""Fleet usage attribution + capacity signals (ISSUE 16).

The headline invariant is CONSERVATION of the two-sided usage ledger:
whatever the engines actually spend (the process-global
``gridllm_usage_engine_*`` counters, incremented only after a result's
publishes succeeded) equals what the owning shards attribute to tenants
(the per-scheduler ``gridllm_usage_*`` counters) — per token kind and
per resource, exactly, across a 2-gateway/2-shard fleet with a
SIGKILL-style worker loss mid-decode (the killed attempt must stay
invisible on BOTH sides) and across a disagg prefill→decode handoff
(whose migrated bytes must land on both sides once).

The kill facade here RAISES on publish, unlike test_fault_tolerance's
silent PartitionableBus: a worker whose result publish silently returns
would still count its usage engine-side while the shard never sees the
payload — the raising facade is what a real dead connection does, and
what the worker's publish-then-account ordering is designed for.
"""

from __future__ import annotations

import asyncio
import hashlib
import re
import uuid

import pytest

from gridllm_tpu.bus import InMemoryBus
from gridllm_tpu.controlplane.client import GatewaySubmitter
from gridllm_tpu.controlplane.partition import shard_of
from gridllm_tpu.controlplane.shard import SchedulerShard, wait_for_ownership
from gridllm_tpu.controlplane.status import FleetView, StatusPublisher
from gridllm_tpu.engine import EngineConfig, InferenceEngine
from gridllm_tpu.obs import MetricsRegistry
from gridllm_tpu.obs import usage as usage_mod
from gridllm_tpu.obs.capacity import (
    DemandTracker,
    _scale_hint,
    aggregate_worker_capacity,
    merge_capacity,
)
from gridllm_tpu.obs.usage import (
    TenantLRU,
    UsageAccountant,
    account_engine_usage,
    build_usage,
    engine_usage_totals,
    resolve_tenant,
)
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.config import (
    Config,
    ControlPlaneConfig,
    SchedulerConfig,
    WorkerConfig,
)
from gridllm_tpu.utils.types import InferenceRequest
from gridllm_tpu.worker.service import WorkerService

from .helpers import FakeWorker, fast_config

MODEL = "tiny-llama"
PROMPT = "the quick brown fox jumps over the lazy dog " * 2
N_PREDICT = 48
CHAOS_TOKENS = 4


def make_engine(**kw) -> InferenceEngine:
    cfg = dict(
        model=MODEL, max_slots=2, page_size=8, num_pages=96,
        max_pages_per_slot=16, prefill_buckets=(16, 64, 128), seed=42,
        prefill_chunk=16,
    )
    cfg.update(kw)
    return InferenceEngine(EngineConfig(**cfg))


# ------------------------------------------------- tenant resolution + LRU


def test_resolve_tenant_header_hash_anonymous():
    assert resolve_tenant({}) == "anonymous"
    # configured header wins, sanitized to a safe label value
    assert resolve_tenant({"X-GridLLM-Tenant": "acme corp!"}) == "acme_corp_"
    assert resolve_tenant({"x-gridllm-tenant": "a.b:c-d_e"}) == "a.b:c-d_e"
    assert resolve_tenant({"X-GridLLM-Tenant": "t" * 100}) == "t" * 64
    # Authorization fallback: a stable truncated digest, never the key
    auth = "Bearer sk-secret-123"
    digest = hashlib.sha256(auth.encode()).hexdigest()[:12]
    assert resolve_tenant({"Authorization": auth}) == f"key-{digest}"
    assert "sk-secret" not in resolve_tenant({"Authorization": auth})
    # the explicit header beats the Authorization fallback
    assert resolve_tenant({"X-GridLLM-Tenant": "acme",
                           "Authorization": auth}) == "acme"


def test_tenant_lru_bounds_label_cardinality():
    lru = TenantLRU(cap=2)
    assert lru.label("a") == "a"
    assert lru.label("b") == "b"
    # full: a new tenant folds into the overflow bucket...
    assert lru.label("c") == "other"
    # ...while resident tenants keep their own label
    assert lru.label("a") == "a"
    assert lru.label("") == "other"  # anonymous competes like anyone else


def test_build_usage_and_engine_ledger_roundtrip():
    before = engine_usage_totals()
    u = build_usage(tenant="acme", model="m-roundtrip",
                    prompt_tokens=11, output_tokens=7,
                    prefix_saved_tokens=3, spec_wasted_tokens=2,
                    decode_device_s=0.5, kv_page_s=1.25,
                    migrated_bytes=4096)
    assert u["tenant"] == "acme" and u["model"] == "m-roundtrip"
    assert u["promptTokens"] == 11 and u["outputTokens"] == 7
    account_engine_usage(u)
    after = engine_usage_totals()
    # the engine counters are process-global: assert the DIFF, not totals
    assert after["prompt"] - before.get("prompt", 0.0) == 11
    assert after["output"] - before.get("output", 0.0) == 7
    assert after["prefix_saved"] - before.get("prefix_saved", 0.0) == 3
    assert after["spec_wasted"] - before.get("spec_wasted", 0.0) == 2


def test_usage_accountant_folds_exactly_once_and_snapshots():
    acc = UsageAccountant(MetricsRegistry(), lru_cap=2)
    u = build_usage(tenant="acme", model="m1", prompt_tokens=10,
                    output_tokens=5, decode_device_s=0.25, kv_page_s=0.5,
                    migrated_bytes=128)
    acc.account(u, "completed")
    acc.account(None, "completed")  # no payload → no-op, never a crash
    acc.note_outcome("acme", "m1", "failed")
    acc.account(dict(u, tenant="burst-1"), "completed")
    acc.account(dict(u, tenant="burst-2"), "duplicate")  # LRU full → other
    totals = acc.token_totals()
    assert totals["prompt"] == 30 and totals["output"] == 15
    snap = acc.snapshot()
    cell = snap["tenants"]["acme"]["m1"]
    assert cell["outcomes"] == {"completed": 1, "failed": 1}
    assert cell["migratedBytes"] == 128
    assert cell["seconds"]["decode_device"] == pytest.approx(0.25)
    assert snap["tenants"]["other"]["m1"]["outcomes"]["duplicate"] == 1


# --------------------------------------------------- demand/capacity model


def test_scale_hint_steers_toward_target_utilization():
    # no workers: live demand asks for the first replica
    assert _scale_hint(workers=0, utilization=0.0, arrival_rate=0.0,
                       queue_depth=0) == 0
    assert _scale_hint(workers=0, utilization=0.0, arrival_rate=1.0,
                       queue_depth=0) == 1
    # saturated: ceil(2 * 1.0 / 0.8) = 3 workers needed
    assert _scale_hint(workers=2, utilization=1.0, arrival_rate=5.0,
                       queue_depth=0) == 1
    # a standing queue always asks for at least one more
    assert _scale_hint(workers=2, utilization=0.5, arrival_rate=1.0,
                       queue_depth=3) == 1
    # scale-down never drops below a single replica
    assert _scale_hint(workers=4, utilization=0.0, arrival_rate=0.0,
                       queue_depth=0) == -3


def test_aggregate_worker_capacity_sums_heartbeat_blocks():
    class W:
        def __init__(self, mc):
            self.modelCapacity = mc

    agg = aggregate_worker_capacity([
        W({"m1": {"slotsFree": 1, "slotsTotal": 2, "kvPagesFree": 10}}),
        W({"m1": {"slotsFree": 2, "slotsTotal": 2, "kvPagesFree": 4},
           "m2": {"slotsFree": 1, "slotsTotal": 1, "kvPagesFree": 3}}),
        W(None),  # a worker that advertises nothing contributes nothing
    ])
    assert agg["m1"] == {"slotsFree": 3, "slotsTotal": 4,
                         "kvPagesFree": 14, "workers": 2}
    assert agg["m2"]["workers"] == 1


def test_demand_tracker_snapshot_agrees_with_its_gauges():
    reg = MetricsRegistry()
    queues = {"m1": 2}
    caps = {"m1": {"slotsFree": 1, "slotsTotal": 4, "kvPagesFree": 10,
                   "workers": 2}}
    # an hour-long half-life makes decay negligible inside the test
    t = DemandTracker(reg, halflife_s=3600.0,
                      queue_depths=lambda: queues,
                      worker_capacity=lambda: caps)
    for _ in range(4):
        t.note_arrival("m1")
    t.note_dispatch("m1", 0.5)
    t.note_completion("m1", 2.0)
    m = t.snapshot()["models"]["m1"]
    assert m["queueDepth"] == 2
    assert m["arrivalRate"] > 0 and m["serviceRate"] > 0
    assert m["waitEwmaS"] == pytest.approx(0.5, rel=0.01)
    assert m["serviceEwmaS"] == pytest.approx(2.0, rel=0.01)
    assert m["utilization"] == pytest.approx(0.75, abs=0.01)
    assert m["headroom"] == {"slots": 1, "kvPages": 10}
    assert m["slotsTotal"] == 4 and m["workers"] == 2
    assert m["scaleHint"] >= 1  # standing queue
    # the gauges /metrics renders show the SAME numbers as the JSON
    t._collect()
    assert t._g_queue.value(model="m1") == m["queueDepth"]
    assert t._g_hint.value(model="m1") == m["scaleHint"]
    assert t._g_headroom.value(model="m1", resource="slots") == 1
    assert t._g_headroom.value(model="m1", resource="kv_pages") == 10


def test_merge_capacity_sums_demand_maxes_headroom():
    def snap(arrival, queue, wait, slots_free):
        return {"halflifeS": 60.0, "models": {"m1": {
            "arrivalRate": arrival, "serviceRate": 0.5,
            "queueDepth": queue, "waitEwmaS": wait,
            "headroom": {"slots": slots_free, "kvPages": slots_free * 4},
            "slotsTotal": 4, "workers": 2}}}

    merged = merge_capacity([snap(1.0, 2, 1.0, 1), snap(3.0, 1, 2.0, 2)])
    assert merged["shards"] == 2
    m = merged["models"]["m1"]
    # demand is partitioned across shards → sums
    assert m["arrivalRate"] == 4.0
    assert m["serviceRate"] == 1.0
    assert m["queueDepth"] == 3
    # worker headroom is the SAME workers seen twice → max, never sum
    assert m["headroom"] == {"slots": 2, "kvPages": 8}
    assert m["slotsTotal"] == 4 and m["workers"] == 2
    # arrival-weighted wait: (1.0*1 + 2.0*3) / 4
    assert m["waitEwmaS"] == pytest.approx(1.75, abs=0.01)
    assert m["utilization"] == pytest.approx(0.5, abs=0.01)
    assert "scaleHint" in m


# ------------------------------------------- gateway stamping end to end


async def test_gateway_stamps_tenant_on_success_and_failure_paths():
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.gateway.app import create_app

    bus = InMemoryBus()
    await bus.connect()
    cfg = fast_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    ok_worker = FakeWorker(bus, "w-ok", ["m1"], max_concurrent=4)
    bad_worker = FakeWorker(bus, "w-bad", ["m2"], fail_times=5,
                            fail_retryable=False)
    await ok_worker.start()
    await bad_worker.start()
    config = Config()
    config.scheduler = cfg
    app = create_app(bus, registry, scheduler, config)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        await bus.flush()
        # success path: the sanitized header value rides the root span
        resp = await client.post(
            "/inference", json={"model": "m1", "prompt": "x"},
            headers={"X-GridLLM-Tenant": "acme corp!"})
        assert resp.status == 200
        rid = (await resp.json())["id"]
        spans = scheduler.tracer.export(rid)
        root = next(s for s in spans if s["name"] == "gateway.request")
        assert root["meta"]["tenant"] == "acme_corp_"

        # failure path: the Authorization-hash tenant lands in the usage
        # ledger under outcome=failed (no payload — the job never ran)
        auth = "Bearer sk-usage-test"
        tenant = "key-" + hashlib.sha256(auth.encode()).hexdigest()[:12]
        resp = await client.post(
            "/inference", json={"model": "m2", "prompt": "x"},
            headers={"Authorization": auth})
        assert resp.status >= 400
        assert scheduler.usage.requests.value(
            tenant=tenant, model="m2", outcome="failed") == 1

        # /admin/capacity agrees with /metrics on the decay-stable
        # integers (the acceptance criterion's agreement check)
        cap = await (await client.get("/admin/capacity")).json()
        assert cap["shard"]["role"] == "local"
        assert cap["models"]["m1"]["queueDepth"] == 0
        assert cap["models"]["m1"]["arrivalRate"] > 0
        # FakeWorkers advertise no modelCapacity → no workers → live
        # demand asks for the first replica
        assert cap["models"]["m1"]["workers"] == 0
        assert cap["models"]["m1"]["scaleHint"] == 1
        assert cap["usage"]["tenants"][tenant]["m2"]["outcomes"] == {
            "failed": 1}
        text = await (await client.get("/metrics")).text()
        for model in ("m1", "m2"):
            mq = re.search(
                r'gridllm_capacity_queue_depth\{model="%s"\} (\S+)' % model,
                text)
            assert mq, f"no queue-depth gauge rendered for {model}"
            assert float(mq.group(1)) == cap["models"][model]["queueDepth"]
        mh = re.search(
            r'gridllm_capacity_scale_hint\{model="m1"\} (\S+)', text)
        assert mh and float(mh.group(1)) == 1
        assert "gridllm_usage_requests_total" in text
        assert tenant in text  # the tenant label reaches the exposition
    finally:
        await client.close()
        await ok_worker.stop(announce=False)
        await bad_worker.stop(announce=False)
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()


# ------------------------------------------------- conservation helpers


def _engine_token_totals() -> dict[str, float]:
    return dict(engine_usage_totals())


def _engine_seconds_totals() -> dict[str, float]:
    out: dict[str, float] = {}
    for labels, value in usage_mod._ENGINE_SECONDS.items():
        r = dict(labels).get("resource", "")
        out[r] = out.get(r, 0.0) + value
    return out


def _engine_migrated_total() -> float:
    return sum(v for _, v in usage_mod._ENGINE_MIGRATED.items())


def _diff(after: dict[str, float], before: dict[str, float]) -> dict[str, float]:
    return {k: v - before.get(k, 0.0) for k, v in after.items()
            if v - before.get(k, 0.0) > 1e-9}


def _shard_token_totals(schedulers) -> dict[str, float]:
    out: dict[str, float] = {}
    for s in schedulers:
        for kind, v in s.usage.token_totals().items():
            out[kind] = out.get(kind, 0.0) + v
    return out


def _shard_seconds_totals(schedulers) -> dict[str, float]:
    out: dict[str, float] = {}
    for s in schedulers:
        for labels, v in s.usage.seconds.items():
            r = dict(labels)["resource"]
            out[r] = out.get(r, 0.0) + v
    return out


def _shard_migrated(schedulers) -> float:
    return sum(v for s in schedulers for _, v in s.usage.migrated.items())


def _shard_outcomes(schedulers) -> dict[str, int]:
    out: dict[str, int] = {}
    for s in schedulers:
        for labels, v in s.usage.requests.items():
            o = dict(labels)["outcome"]
            out[o] = out.get(o, 0) + int(v)
    return out


class ConnLossBus:
    """Per-worker facade whose death RAISES on every outbound call — a
    torn TCP connection, not a black hole. This matters for the ledger:
    the worker accounts engine-side usage only after its result publish
    SUCCEEDS, so a raising publish keeps the killed attempt invisible on
    both sides of the conservation invariant (a silently-dropping bus
    would let the worker count usage the shard never receives)."""

    def __init__(self, inner):
        self._inner = inner
        self.dead = False

    def __getattr__(self, name):
        return getattr(self._inner, name)

    async def publish(self, channel: str, message: str):
        if self.dead:
            raise ConnectionError("bus connection lost")
        return await self._inner.publish(channel, message)

    async def hset(self, key: str, field: str, value: str):
        if self.dead:
            raise ConnectionError("bus connection lost")
        return await self._inner.hset(key, field, value)

    async def set_with_expiry(self, key: str, value: str, ttl_s: float):
        if self.dead:
            raise ConnectionError("bus connection lost")
        return await self._inner.set_with_expiry(key, value, ttl_s)


def _job_for_shard(idx: int, num_shards: int = 2) -> str:
    while True:
        jid = f"job-{uuid.uuid4().hex[:10]}"
        if shard_of(jid, num_shards) == idx:
            return jid


def usage_fleet_config() -> SchedulerConfig:
    """Sub-second liveness (the killed worker must orphan fast) with a
    generous job timeout (first-compile costs)."""
    return SchedulerConfig(
        worker_heartbeat_timeout_ms=600,
        worker_cleanup_interval_ms=100,
        connection_monitor_interval_ms=100,
        quick_disconnect_window_ms=400,
        orphan_assign_threshold_ms=200,
        job_timeout_ms=180_000,
        retry_attempts=3,
        retry_delay_ms=50,
        sweep_interval_ms=100,
    )


async def _settle_outcomes(bus, schedulers, want: int,
                           timeout_s: float = 10.0) -> None:
    """The client sees job:result before the owning shard's job:completed
    handler folds the ledger — wait for the fold, don't race it."""
    for _ in range(int(timeout_s / 0.05)):
        await bus.flush()
        got = _shard_outcomes(schedulers)
        if got.get("completed", 0) + got.get("duplicate", 0) >= want:
            return
        await asyncio.sleep(0.05)
    raise AssertionError(
        f"shards never folded {want} completions: {_shard_outcomes(schedulers)}")


# ------------------------- THE conservation differential (2×2 fleet + kill)


async def test_conservation_two_shard_fleet_with_worker_kill():
    """Acceptance criterion: a 2-gateway/2-shard fleet serves one request
    per partition; the worker serving the shard-0 request is killed
    mid-decode (raising bus). The resumed execution completes on the
    survivor, and the per-tenant shard ledgers sum EXACTLY to the
    engine-side counters — the killed attempt is invisible on both
    sides, per token kind and per resource-second."""
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.gateway.app import create_app

    bus = InMemoryBus()
    await bus.connect()
    cfg = usage_fleet_config()
    shards = []
    for i in range(2):
        reg = WorkerRegistry(bus, cfg)
        sh = SchedulerShard(
            bus, reg, cfg,
            ControlPlaneConfig(mode="gateway", num_shards=2, shard_id=i,
                               lease_ttl_ms=400, renew_interval_ms=80,
                               status_interval_ms=100),
            member_id=f"shard-{i}", settle_s=0.01 + 0.005 * i)
        await reg.initialize()
        await sh.start()
        shards.append(sh)
    assert await wait_for_ownership(shards, 2, timeout_s=5.0)
    gws = []
    for i in range(2):
        reg = WorkerRegistry(bus, cfg, observer=True)
        gw = GatewaySubmitter(bus, reg, cfg, member_id=f"gw-{i}")
        await reg.initialize()
        await gw.initialize()
        gws.append(gw)
    workers: list[WorkerService] = []
    for i in range(2):
        svc = WorkerService(
            ConnLossBus(bus), {MODEL: make_engine()},
            WorkerConfig(worker_id=f"cap-w{i}", heartbeat_interval_ms=150),
            stream_flush_ms=5)
        svc._snap_every = 2
        await svc.start()
        workers.append(svc)
    await asyncio.sleep(0.4)  # first heartbeats land
    tok0 = _engine_token_totals()
    sec0 = _engine_seconds_totals()
    scheds = [sh.scheduler for sh in shards]
    try:
        # capacity signals from REAL heartbeats: both workers advertise
        # per-model slot/KV headroom, every shard's registry sums them
        m = None
        for _ in range(100):
            m = shards[0].scheduler.capacity.snapshot()["models"].get(MODEL)
            if m and m["workers"] == 2:
                break
            await asyncio.sleep(0.05)
        assert m and m["workers"] == 2, m
        assert m["slotsTotal"] == 4  # 2 workers × max_slots=2
        assert m["headroom"]["slots"] == 4 and m["headroom"]["kvPages"] > 0

        async def run(gw, jid: str, chaos=None):
            chunks: list[str] = []

            async def on_chunk(c) -> None:
                chunks.append(c.response)

            req = InferenceRequest(
                id=jid, model=MODEL, prompt=PROMPT, stream=True,
                options={"temperature": 0, "num_predict": N_PREDICT},
                metadata={"requestType": "inference", "tenant": "acme"})
            task = asyncio.create_task(gw.submit_streaming_job(
                req, on_chunk, timeout_ms=120_000))
            if chaos is not None:
                owner = shards[shard_of(jid, 2)].scheduler
                for _ in range(9000):
                    snap = owner._resume_snap.get(jid)
                    if snap is not None and len(snap["tokens"]) >= CHAOS_TOKENS:
                        break
                    await asyncio.sleep(0.01)
                else:
                    raise AssertionError("decode never reached the chaos point")
                await chaos(jid)
            res = await task
            return "".join(chunks), res

        async def kill(jid: str) -> None:
            wid = shards[0].scheduler.active_jobs[jid].workerId
            victim = next(w for w in workers if w.worker_id == wid)
            victim.bus.dead = True  # type: ignore[attr-defined]

        # chaos request on shard 0's partition, clean one on shard 1's
        text0, res0 = await run(gws[0], _job_for_shard(0), chaos=kill)
        assert res0.success, res0.error
        assert text0
        text1, res1 = await run(gws[1], _job_for_shard(1))
        assert res1.success, res1.error

        st0 = shards[0].scheduler
        assert int(st0._jobs_total.value(event="orphaned")) >= 1
        assert int(st0._resume_total.value(event="stamped")) >= 1

        await _settle_outcomes(bus, scheds, want=2)
        outcomes = _shard_outcomes(scheds)
        # exactly the two resolving executions — the killed attempt never
        # published, so there is no duplicate to account
        assert outcomes.get("completed", 0) == 2, outcomes
        assert outcomes.get("duplicate", 0) == 0, outcomes

        # CONSERVATION: engine-side diff == shard-side sums, per kind
        tok_diff = _diff(_engine_token_totals(), tok0)
        assert tok_diff.get("prompt", 0) > 0
        assert tok_diff.get("output", 0) > 0
        shard_tok = _shard_token_totals(scheds)
        for kind in set(tok_diff) | set(shard_tok):
            assert shard_tok.get(kind, 0.0) == pytest.approx(
                tok_diff.get(kind, 0.0)), kind
        sec_diff = _diff(_engine_seconds_totals(), sec0)
        assert sec_diff.get("decode_device", 0) > 0
        shard_sec = _shard_seconds_totals(scheds)
        for resource in set(sec_diff) | set(shard_sec):
            assert shard_sec.get(resource, 0.0) == pytest.approx(
                sec_diff.get(resource, 0.0)), resource
        # attribution: every accounted token belongs to the stamped tenant
        for s in scheds:
            tenants = s.usage.snapshot()["tenants"]
            assert set(tenants) <= {"acme"}, tenants

        # any gateway replica serves the fleet-merged capacity view
        view = FleetView(bus, gws[0].metrics, stale_after_ms=5000)
        await view.start()
        pubs = [StatusPublisher(bus, sh.scheduler, "shard", sh.member_id,
                                100, lease=sh.lease) for sh in shards]
        for p in pubs:
            await p.publish_once()
        await bus.flush()
        app = create_app(bus, gws[0].registry, gws[0], Config(), fleet=view)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            body = await (await client.get("/admin/capacity")).json()
            assert body["shard"]["role"] == "gateway"
            assert body["fleet"]["numShards"] == 2
            assert set(body["fleet"]["perMember"]) == {"shard-0", "shard-1"}
            fm = body["fleet"]["fleet"]["models"][MODEL]
            assert fm["queueDepth"] == 0
            assert fm["arrivalRate"] > 0  # both shards' demand summed
        finally:
            await client.close()
            await view.stop()
    finally:
        for w in workers:
            w.bus.dead = False  # resurrect so teardown can announce/stop
        for w in workers:
            await w.stop(announce=False)
        for gw in gws:
            await gw.shutdown()
            await gw.registry.shutdown()
        for sh in shards:
            await sh.stop()
            await sh.registry.shutdown()
        await bus.disconnect()


# ---------------------------------------- disagg handoff conservation


async def test_disagg_handoff_conserves_and_attributes_migration():
    """Prefill on A, decode on B after a KV migration: the handoff
    itself carries NO usage payload — only the worker that RESOLVES the
    request publishes one, with the imported KV bytes attributed as
    migration cost. Conservation must hold across the handoff, and the
    migrated bytes must appear once on each side of the ledger."""
    bus = InMemoryBus()
    await bus.connect()
    cfg = SchedulerConfig(worker_heartbeat_timeout_ms=60_000,
                          job_timeout_ms=180_000, sweep_interval_ms=200)
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    workers = []
    for i, role in enumerate(["prefill", "decode"]):
        svc = WorkerService(
            bus, {MODEL: make_engine()},
            WorkerConfig(worker_id=f"w-{role}-{i}", role=role,
                         heartbeat_interval_ms=200),
            stream_flush_ms=5)
        await svc.start()
        workers.append(svc)
    await asyncio.sleep(0.5)
    tok0 = _engine_token_totals()
    sec0 = _engine_seconds_totals()
    mig0 = _engine_migrated_total()
    try:
        chunks: list[str] = []

        async def on_chunk(c) -> None:
            chunks.append(c.response)

        req = InferenceRequest(
            id=f"job-{uuid.uuid4().hex[:8]}", model=MODEL, prompt=PROMPT,
            stream=True, options={"temperature": 0, "num_predict": 16},
            metadata={"requestType": "inference", "tenant": "acme"})
        res = await scheduler.submit_streaming_job(req, on_chunk,
                                                   timeout_ms=120_000)
        assert res.success, res.error
        assert res.workerId.startswith("w-decode")
        await _settle_outcomes(bus, [scheduler], want=1)

        mig_diff = _engine_migrated_total() - mig0
        assert mig_diff > 0  # the migration really moved KV bytes
        assert _shard_migrated([scheduler]) == pytest.approx(mig_diff)
        tok_diff = _diff(_engine_token_totals(), tok0)
        shard_tok = scheduler.usage.token_totals()
        for kind in set(tok_diff) | set(shard_tok):
            assert shard_tok.get(kind, 0.0) == pytest.approx(
                tok_diff.get(kind, 0.0)), kind
        sec_diff = _diff(_engine_seconds_totals(), sec0)
        assert sec_diff.get("decode_device", 0) > 0
        assert sec_diff.get("kv_page", 0) > 0
        cell = scheduler.usage.snapshot()["tenants"]["acme"][MODEL]
        assert cell["migratedBytes"] > 0
        assert cell["outcomes"] == {"completed": 1}
    finally:
        for svc in workers:
            await svc.stop(announce=False)
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()
