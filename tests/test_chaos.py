"""Fault injection: a REAL worker process is SIGKILLed mid-job and the
cluster recovers end-to-end.

SURVEY.md §5.3 called this the reference's own CI gap worth closing
("CI never kills a worker mid-job"). Unit-level orphan tests exist
(tests/test_scheduler.py); this is the full-stack version: gateway HTTP →
scheduler → REAL RESP broker → a real engine worker in a child process
that dies abruptly (no unregister, heartbeat key left to expire) while
holding the job → 3-tier liveness detects it → the job is orphan-promoted
and held → a SECOND real worker registers → the job completes through it
and the original HTTP request succeeds.

ISSUE 2 adds the OTHER death mode: a worker that wedges mid-decode WITHOUT
exiting. Its heartbeat keeps beating, so no liveness tier ever fires — only
the hang watchdog (obs/watchdog.py) can see the stalled stream, dump a
post-mortem, and requeue the job.
"""

import asyncio
import os
import signal
import subprocess
import sys
from pathlib import Path

from aiohttp.test_utils import TestClient, TestServer

from gridllm_tpu.bus import create_bus
from gridllm_tpu.bus.broker import GridBusBroker
from gridllm_tpu.engine import EngineConfig, InferenceEngine
from gridllm_tpu.gateway.app import create_app
from gridllm_tpu.obs import default_flight_recorder
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.config import (
    Config,
    SchedulerConfig,
    WatchdogConfig,
    WorkerConfig,
)
from gridllm_tpu.utils.types import StreamChunk, iso_now
from gridllm_tpu.worker.service import WorkerService

from .helpers import FakeWorker

CHILD = Path(__file__).with_name("chaos_worker_child.py")


def _chaos_config() -> SchedulerConfig:
    """Sub-second failure detection but a generous job timeout (the child
    pays first-compile costs while holding the job)."""
    return SchedulerConfig(
        worker_heartbeat_timeout_ms=600,
        worker_cleanup_interval_ms=100,
        connection_monitor_interval_ms=100,
        quick_disconnect_window_ms=400,
        orphan_assign_threshold_ms=200,
        job_timeout_ms=180_000,
        retry_attempts=2,
        retry_delay_ms=50,
        sweep_interval_ms=100,
    )


async def test_worker_sigkill_mid_job_recovers_on_second_worker():
    broker = GridBusBroker()
    await broker.start(port=0)

    env = {**os.environ, "PYTHONPATH": str(CHILD.parent.parent)}
    env.pop("XLA_FLAGS", None)
    victim_id = "chaos-victim"
    child = subprocess.Popen(
        [sys.executable, str(CHILD), str(broker.port), victim_id],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )

    url = f"resp://127.0.0.1:{broker.port}"
    bus = create_bus(url)
    await bus.connect()
    sched_cfg = _chaos_config()
    registry = WorkerRegistry(bus, sched_cfg)
    scheduler = JobScheduler(bus, registry, sched_cfg)
    await registry.initialize()
    await scheduler.initialize()
    config = Config()
    config.scheduler = sched_cfg
    app = create_app(bus, registry, scheduler, config)
    client = TestClient(TestServer(app))
    await client.start_server()

    # spy connection: detect the assignment landing on the victim
    spy = create_bus(url)
    await spy.connect()
    assigned = asyncio.Event()

    async def on_job(_ch: str, _raw: str) -> None:
        assigned.set()

    await spy.subscribe(f"worker:{victim_id}:job", on_job)

    second: WorkerService | None = None
    try:
        # wait for the victim to register (engine build takes a while)
        for _ in range(1200):
            if registry.get_workers_with_model("tiny-llama"):
                break
            await asyncio.sleep(0.1)
        assert registry.get_workers_with_model("tiny-llama"), (
            child.stdout.read() if child.poll() is not None else
            "victim never registered")

        async def request():
            return await client.post("/ollama/api/generate", json={
                "model": "tiny-llama", "prompt": "chaos", "stream": False,
                "options": {"temperature": 0, "num_predict": 8, "seed": 0},
            })

        req_task = asyncio.create_task(request())

        # the instant the job lands on the victim, SIGKILL it: no
        # unregister, no NACK — the heartbeat key just stops refreshing
        await asyncio.wait_for(assigned.wait(), 30)
        child.send_signal(signal.SIGKILL)
        child.wait(timeout=10)

        # bring up the replacement AFTER the kill, so recovery must hold
        # the orphaned job until a model owner exists again
        second = WorkerService(
            bus, {"tiny-llama": InferenceEngine(EngineConfig(
                model="tiny-llama", max_slots=2, page_size=8, num_pages=32,
                max_pages_per_slot=4, prefill_buckets=(16, 32),
            ))},
            WorkerConfig(worker_id="chaos-replacement",
                         heartbeat_interval_ms=150,
                         resource_monitor_interval_ms=500),
            stream_flush_ms=5,
        )
        await second.start()

        resp = await asyncio.wait_for(req_task, 120)
        body = await resp.json()
        assert resp.status == 200, body
        assert body["done"] is True, body
        assert body.get("eval_count", 0) >= 1, body
        assert second.total_processed == 1  # the replacement served it
        # the victim is gone from the registry
        assert all(
            w.workerId != victim_id
            for w in registry.get_online_workers()
        )
        # observability (ISSUE 1): the recovery is visible in the metrics —
        # the job was orphaned then completed — and the failure storm left
        # no leaked active span in the tracer
        stats = scheduler.get_stats()
        assert stats["totalJobsOrphaned"] >= 1
        assert stats["totalJobsCompleted"] == 1
        assert scheduler.tracer.active_count() == 0, (
            scheduler.tracer.active_ids())
        text = scheduler.metrics.render()
        assert 'gridllm_scheduler_jobs_total{event="orphaned"}' in text
        assert 'gridllm_workers_removed_total' in text
    finally:
        if child.poll() is None:
            child.kill()
        await client.close()
        if second is not None:
            await second.stop()
        await scheduler.shutdown()
        await registry.shutdown()
        await spy.disconnect()
        await bus.disconnect()
        await broker.stop()


class _WedgedWorker(FakeWorker):
    """Streams one token frame, then stops making progress WITHOUT exiting:
    heartbeats continue, the job is never completed, never failed. The
    liveness tiers see a healthy worker — only the watchdog can tell."""

    async def _execute(self, assignment):
        self.current_jobs += 1
        await self.bus.publish(f"job:stream:{assignment.jobId}", StreamChunk(
            id=assignment.jobId, model=assignment.request.model,
            created_at=iso_now(), response="x", done=False,
        ).model_dump_json())
        try:
            await asyncio.sleep(3600)
        finally:
            self.current_jobs -= 1


async def test_wedged_worker_detected_dumped_and_requeued():
    """ISSUE 2 acceptance: a worker stalled mid-decode is detected by the
    watchdog within its per-phase deadline, an auto dump names the hung
    request/phase/worker, and the job is requeued (reason hang) and served
    by a healthy worker — all over a REAL RESP broker."""
    recorder = default_flight_recorder()
    recorder.clear()
    broker = GridBusBroker()
    await broker.start(port=0)
    url = f"resp://127.0.0.1:{broker.port}"
    bus = create_bus(url)
    await bus.connect()
    sched_cfg = _chaos_config()
    stall_ms = 400
    registry = WorkerRegistry(bus, sched_cfg)
    scheduler = JobScheduler(
        bus, registry, sched_cfg,
        watchdog_config=WatchdogConfig(
            interval_ms=100, decode_stall_ms=stall_ms,
            dispatch_deadline_ms=60_000, requeue=True))
    await registry.initialize()
    await scheduler.initialize()
    config = Config()
    config.scheduler = sched_cfg
    app = create_app(bus, registry, scheduler, config)
    client = TestClient(TestServer(app))
    await client.start_server()

    wedged_bus = create_bus(url)
    await wedged_bus.connect()
    wedged = _WedgedWorker(wedged_bus, "chaos-wedged", ["tiny-model"],
                           heartbeat_interval_s=0.15)
    healthy_bus = create_bus(url)
    await healthy_bus.connect()
    healthy = FakeWorker(healthy_bus, "chaos-healthy", ["tiny-model"],
                         stream_tokens=["a", "b"],
                         heartbeat_interval_s=0.15)
    try:
        await wedged.start()
        for _ in range(100):
            if registry.get_workers_with_model("tiny-model"):
                break
            await asyncio.sleep(0.05)

        req_task = asyncio.create_task(client.post(
            "/ollama/api/generate",
            json={"model": "tiny-model", "prompt": "chaos"}))

        # detection must land within the deadline + a couple of sweeps
        t0 = asyncio.get_running_loop().time()
        detected_at = None
        while asyncio.get_running_loop().time() - t0 < 15:
            await asyncio.sleep(0.05)
            if scheduler.metrics.get("gridllm_hangs_total").value(
                    phase="decode-step"):
                detected_at = asyncio.get_running_loop().time()
                break
        assert detected_at is not None, "watchdog never fired"

        # the healthy worker arrives AFTER detection; the requeued job must
        # complete through it and resolve the original HTTP request
        await healthy.start()
        resp = await asyncio.wait_for(req_task, 30)
        assert resp.status == 200
        await resp.text()
        assert healthy.processed, "replacement never served the job"
        assert wedged.cancelled, "wedged worker never told to drop the job"

        # the auto dump names the hung request, phase, and worker, and the
        # hang is on the metrics + the trace
        hang_dumps = [d for d in recorder.auto_dumps()
                      if d["reason"].startswith("hang:")]
        assert hang_dumps
        hang = hang_dumps[0]["hang"]
        assert hang["phase"] == "decode-step"
        assert hang["worker"] == "chaos-wedged"
        spans = scheduler.tracer.export(hang["requestId"])
        assert any(s["name"] == "watchdog.hang" for s in spans)
        # job:completed (lifecycle channel) may trail job:result (waiter
        # channel) on a real broker — give the handler a moment
        for _ in range(100):
            if scheduler.get_stats()["totalJobsCompleted"]:
                break
            await asyncio.sleep(0.05)
        stats = scheduler.get_stats()
        assert stats["totalJobsOrphaned"] >= 1  # hang requeue path
        assert stats["totalJobsCompleted"] == 1
        assert scheduler.tracer.active_count() == 0, (
            scheduler.tracer.active_ids())
        # /admin/dump serves the artifact over HTTP too
        body = await (await client.get("/admin/dump")).json()
        assert any(d["reason"].startswith("hang:")
                   for d in body["autoDumps"])
    finally:
        await client.close()
        await wedged.stop(announce=False)
        await healthy.stop(announce=False)
        await scheduler.shutdown()
        await registry.shutdown()
        await wedged_bus.disconnect()
        await healthy_bus.disconnect()
        await bus.disconnect()
        await broker.stop()
