"""Sharding/mesh tests on the virtual 8-device CPU mesh (SURVEY.md §4:
"Multi-host TPU tests can run the real protocol with jax.devices('cpu')
meshes")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from gridllm_tpu.models import llama
from gridllm_tpu.models.configs import get_config
from gridllm_tpu.ops.kvcache import PagedKVCache, PageAllocator
from gridllm_tpu.parallel import (
    MeshConfig,
    build_mesh,
    cache_shardings,
    param_shardings,
)
from gridllm_tpu.parallel.sharding import shard_cache, shard_params

CFG = get_config("tiny-llama")


def test_mesh_config_resolve():
    assert MeshConfig(tp=-1).resolve(8) == (1, 1, 1, 8, 1)
    assert MeshConfig(dp=2, tp=-1).resolve(8) == (1, 2, 1, 4, 1)
    assert MeshConfig(dp=2, ep=2, tp=2, sp=1).resolve(8) == (1, 2, 2, 2, 1)
    assert MeshConfig(pp=2, tp=-1).resolve(8) == (2, 1, 1, 4, 1)
    with pytest.raises(ValueError):
        MeshConfig(dp=3, tp=-1).resolve(8)
    with pytest.raises(ValueError):
        MeshConfig(dp=-1, tp=-1).resolve(8)
    with pytest.raises(ValueError):
        MeshConfig(dp=2, tp=2).resolve(8)


def test_param_shardings_layout():
    mesh = build_mesh(MeshConfig(dp=4, tp=2))  # tp=2 divides KVH=2 and heads=4
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    sh = param_shardings(params, mesh)
    assert sh["layers"]["wq"].spec == P("pp", None, "tp")
    assert sh["layers"]["wo"].spec == P("pp", "tp", None)
    assert sh["layers"]["attn_norm"].spec == P("pp", None)
    assert sh["embed"].spec == P("tp", None)
    # lm_head [E=64, V=256]: both divisible by 2 → vocab sharded
    assert sh["lm_head"].spec == P(None, "tp")


def test_indivisible_dims_fall_back_to_replicated():
    mesh = build_mesh(MeshConfig(tp=-1))  # tp=8
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    sh = param_shardings(params, mesh)
    # wk out dim = KVH*D = 2*16 = 32: divisible by 8 → sharded
    assert sh["layers"]["wk"].spec == P("pp", None, "tp")
    cache = PagedKVCache.create(CFG.num_layers, 8, 4, CFG.num_kv_heads,
                                CFG.head_dim_, 2, 4)
    csh = cache_shardings(cache, mesh)
    # KVH=2 not divisible by tp=8 → pool replicated on that dim
    assert csh.k.spec == P("pp", None, None, None, None)


def test_sharded_forward_matches_single_device():
    params = llama.init_params(CFG, jax.random.PRNGKey(1), dtype=jnp.float32)
    tokens = jnp.asarray([[5, 17, 99, 3, 42, 7, 250, 1]], jnp.int32)
    want = np.asarray(llama.forward(params, CFG, tokens))

    mesh = build_mesh(MeshConfig(dp=4, tp=2))
    sparams = shard_params(params, mesh)
    got = np.asarray(jax.jit(llama.forward, static_argnums=1)(sparams, CFG, tokens))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_sharded_prefill_decode_match_single_device():
    """The full paged pipeline under a tp=2 mesh reproduces unsharded tokens."""
    params = llama.init_params(CFG, jax.random.PRNGKey(2), dtype=jnp.float32)
    prompt = [5, 17, 99, 3, 42]

    def run(params, cache):
        alloc = PageAllocator(16, 8, 8)
        alloc.alloc(0, 16)
        row = jnp.asarray(alloc.table_row(0), jnp.int32)
        padded = jnp.asarray(prompt + [0] * 3, jnp.int32)
        logits, cache = llama.prefill(
            params, CFG, padded, jnp.int32(len(prompt)), cache, jnp.int32(0), row
        )
        out = [int(jnp.argmax(logits))]
        tok = jnp.zeros((cache.max_slots,), jnp.int32).at[0].set(out[0])
        active = jnp.zeros((cache.max_slots,), bool).at[0].set(True)
        for _ in range(4):
            logits, cache = llama.decode_step(params, CFG, tok, cache, active)
            nxt = int(jnp.argmax(logits[0]))
            out.append(nxt)
            tok = tok.at[0].set(nxt)
        return out

    def fresh_cache():
        c = PagedKVCache.create(CFG.num_layers, 16, 8, CFG.num_kv_heads,
                                CFG.head_dim_, 4, 8)
        return PagedKVCache(k=c.k.astype(jnp.float32), v=c.v.astype(jnp.float32),
                            page_table=c.page_table, lengths=c.lengths,
                            page_size=c.page_size)

    want = run(params, fresh_cache())

    mesh = build_mesh(MeshConfig(dp=1, tp=2, sp=-1))  # tp=2, sp absorbs 4
    got = run(shard_params(params, mesh), shard_cache(fresh_cache(), mesh))
    assert got == want


def test_ep_sharded_mixtral_matches_single_device():
    """Mixtral under an ep×tp mesh reproduces unsharded logits — the expert
    einsum must shard on "ep" (weighted combine becomes the all-reduce)."""
    from gridllm_tpu.models import mixtral

    mcfg = get_config("tiny-mixtral")
    params = mixtral.init_params(mcfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    tokens = jnp.asarray([[5, 17, 99, 3, 42, 7, 250, 1]], jnp.int32)
    want = np.asarray(mixtral.forward(params, mcfg, tokens))

    mesh = build_mesh(MeshConfig(dp=2, ep=2, tp=2))  # X=4 experts / ep=2
    sh = param_shardings(params, mesh)
    assert sh["layers"]["we_gate"].spec == P("pp", "ep", None, "tp")
    assert sh["layers"]["we_down"].spec == P("pp", "ep", "tp", None)
    sparams = shard_params(params, mesh)
    got = np.asarray(jax.jit(mixtral.forward, static_argnums=1)(sparams, mcfg, tokens))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("mc,lens", [
    (dict(dp=1, tp=1, sp=-1), [64, 23]),   # sp=8, ragged
    (dict(dp=2, tp=2, sp=2), [64, 64]),    # mixed axes
])
def test_ring_attention_matches_ref(mc, lens):
    from gridllm_tpu.ops.attention import attention_prefill_ref
    from gridllm_tpu.ops.ring_attention import ring_attention

    mesh = build_mesh(MeshConfig(**mc))
    b, t, h, kvh, d = len(lens), 64, 4, 2, 16
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, kvh, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, kvh, d), jnp.float32)
    seq_lens = jnp.asarray(lens, jnp.int32)
    want = np.asarray(attention_prefill_ref(q, k, v, seq_lens))
    got = np.asarray(jax.jit(
        lambda *a: ring_attention(*a, mesh)
    )(q, k, v, seq_lens))
    for i, ln in enumerate(lens):
        np.testing.assert_allclose(got[i, :ln], want[i, :ln],
                                   rtol=1e-5, atol=1e-5)


def test_ring_attention_indivisible_bucket_falls_back():
    from gridllm_tpu.ops.ring_attention import ring_attention
    from gridllm_tpu.ops.attention import attention_prefill_ref

    mesh = build_mesh(MeshConfig(tp=1, sp=-1))  # sp=8; t=20 not divisible
    q = jax.random.normal(jax.random.PRNGKey(6), (1, 20, 2, 8), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(7), (1, 20, 2, 8), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(8), (1, 20, 2, 8), jnp.float32)
    lens = jnp.asarray([20], jnp.int32)
    got = np.asarray(ring_attention(q, k, v, lens, mesh))
    want = np.asarray(attention_prefill_ref(q, k, v, lens))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_sp_sharded_prefill_decode_match_single_device():
    """Full paged pipeline with RING-ATTENTION prefill on an sp mesh
    reproduces single-device greedy tokens (the sequence-parallel
    long-context path end to end: sharded prefill writes the cache, then
    normal decode reads it)."""
    from functools import partial as fpartial

    from gridllm_tpu.ops.ring_attention import ring_attention

    params = llama.init_params(CFG, jax.random.PRNGKey(9), dtype=jnp.float32)
    prompt = [5, 17, 99, 3, 42, 8, 1, 2]  # fills the t=8 bucket

    def run(params, cache, attn=None):
        alloc = PageAllocator(16, 8, 8)
        alloc.alloc(0, 16)
        row = jnp.asarray(alloc.table_row(0), jnp.int32)
        padded = jnp.asarray(prompt, jnp.int32)
        logits, cache = llama.prefill(
            params, CFG, padded, jnp.int32(len(prompt)), cache,
            jnp.int32(0), row, attn=attn,
        )
        out = [int(jnp.argmax(logits))]
        tok = jnp.zeros((cache.max_slots,), jnp.int32).at[0].set(out[0])
        active = jnp.zeros((cache.max_slots,), bool).at[0].set(True)
        for _ in range(4):
            logits, cache = llama.decode_step(params, CFG, tok, cache, active)
            nxt = int(jnp.argmax(logits[0]))
            out.append(nxt)
            tok = tok.at[0].set(nxt)
        return out

    def fresh_cache():
        c = PagedKVCache.create(CFG.num_layers, 16, 8, CFG.num_kv_heads,
                                CFG.head_dim_, 4, 8)
        return PagedKVCache(k=c.k.astype(jnp.float32), v=c.v.astype(jnp.float32),
                            page_table=c.page_table, lengths=c.lengths,
                            page_size=c.page_size)

    want = run(params, fresh_cache())
    mesh = build_mesh(MeshConfig(dp=1, tp=2, sp=4))
    got = run(shard_params(params, mesh), shard_cache(fresh_cache(), mesh),
              attn=fpartial(ring_attention, mesh=mesh))
    assert got == want


def test_sp_prefill_pins_residual_stream_to_sp():
    """VERDICT #9: the sp memory claim must be a checked property, not a
    comment. Structurally assert the prefill graph carries T-axis sharding
    constraints P(None, 'sp', None) on the residual stream (embed + per
    layer), so prefill activations are O(T/sp) by annotation, not GSPMD
    propagation luck. Also check numerics are unchanged vs the jnp oracle."""
    from functools import partial

    from gridllm_tpu.ops.ring_attention import ring_attention

    mesh = build_mesh(MeshConfig(dp=1, tp=1, sp=8))
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    cache = PagedKVCache.create(CFG.num_layers, 16, 8, CFG.num_kv_heads,
                                CFG.head_dim_, 2, 8, dtype=jnp.float32)
    alloc = PageAllocator(16, 8, 8)
    alloc.alloc(0, 64)
    row = jnp.asarray(alloc.table_row(0), jnp.int32)
    tokens = jnp.asarray(np.arange(64) % CFG.vocab_size, jnp.int32)
    attn = partial(ring_attention, mesh=mesh)

    def run(p, tok, c):
        return llama.prefill(p, CFG, tok, jnp.int32(64), c, jnp.int32(0),
                             row, attn=attn, mesh=mesh)

    def count_sp_constraints(jaxpr) -> int:
        n = 0
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "sharding_constraint":
                sh = eqn.params.get("sharding")
                spec = getattr(sh, "spec", None)
                if spec is not None and len(spec) == 3 and spec[1] == "sp":
                    n += 1
            for sub in jax.core.jaxprs_in_params(eqn.params):
                n += count_sp_constraints(sub)
        return n

    jaxpr = jax.make_jaxpr(run)(params, tokens, cache)
    n = count_sp_constraints(jaxpr.jaxpr)
    # embed constraint + 2 per scanned layer body (post-attn, post-mlp)
    assert n >= 3, f"expected >=3 sp sharding constraints, found {n}"

    # numerics: sharded prefill == unsharded oracle
    sharded = shard_params(params, mesh)
    scache = shard_cache(cache, mesh)
    logits_sp, cache_sp = jax.jit(run)(sharded, tokens, scache)
    logits_ref, cache_ref = jax.jit(
        lambda p, tok, c: llama.prefill(p, CFG, tok, jnp.int32(64), c,
                                        jnp.int32(0), row)
    )(params, tokens, cache)
    np.testing.assert_allclose(np.asarray(logits_sp), np.asarray(logits_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(cache_sp.k), np.asarray(cache_ref.k),
                               rtol=1e-4, atol=1e-4)
