"""Gateway API tests: stub worker over the in-memory bus, real HTTP via
aiohttp TestClient (SURVEY.md §7 step 3: 'the differential-shape e2e can run
with a stub worker')."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from gridllm_tpu.bus import InMemoryBus
from gridllm_tpu.gateway.app import create_app
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.config import Config

from .helpers import FakeWorker, fast_config


async def make_client(rate_limit: int | None = None):
    bus = InMemoryBus(key_prefix="G:")
    await bus.connect()
    cfg = fast_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    app_cfg = Config(scheduler=cfg)
    if rate_limit is not None:
        app_cfg.gateway.rate_limit_max_requests = rate_limit
    app = create_app(bus, registry, scheduler, app_cfg)
    client = TestClient(TestServer(app))
    await client.start_server()
    return client, bus, registry, scheduler


async def teardown(client, bus, registry, scheduler, *workers):
    for w in workers:
        await w.stop(announce=False)
    await client.close()
    await scheduler.shutdown()
    await registry.shutdown()
    await bus.disconnect()


async def start_worker(bus, **kw):
    w = FakeWorker(bus, kw.pop("worker_id", "w1"), kw.pop("models", ["m1"]), **kw)
    await w.start()
    await bus.flush()
    return w


async def test_root_summary():
    client, bus, registry, scheduler = await make_client()
    resp = await client.get("/")
    body = await resp.json()
    assert resp.status == 200
    assert body["name"] == "GridLLM-TPU Server"
    assert "workers" in body and "jobs" in body
    await teardown(client, bus, registry, scheduler)


async def test_generate_non_streaming():
    client, bus, registry, scheduler = await make_client()
    w = await start_worker(bus, reply="four")
    resp = await client.post("/ollama/api/generate", json={
        "model": "m1", "prompt": "2+2?", "stream": False})
    body = await resp.json()
    assert resp.status == 200
    # Ollama response shape: all timing fields present
    for key in ("model", "created_at", "response", "done", "context",
                "total_duration", "load_duration", "prompt_eval_count",
                "prompt_eval_duration", "eval_count", "eval_duration"):
        assert key in body, f"missing {key}"
    assert body["response"] == "four" and body["done"] is True
    await teardown(client, bus, registry, scheduler, w)


async def test_generate_streaming_ndjson():
    client, bus, registry, scheduler = await make_client()
    toks = ["a", "b", "c"]
    w = await start_worker(bus, stream_tokens=toks)
    resp = await client.post("/ollama/api/generate", json={
        "model": "m1", "prompt": "go"})  # stream defaults TRUE
    assert resp.status == 200
    assert "ndjson" in resp.headers["Content-Type"]
    lines = [json.loads(l) for l in (await resp.text()).strip().split("\n")]
    assert [l["response"] for l in lines[:-1]] == toks
    assert lines[-1]["done"] is True
    assert lines[-1]["response"] == "abc"
    await teardown(client, bus, registry, scheduler, w)


async def test_generate_empty_prompt_load_unload():
    client, bus, registry, scheduler = await make_client()
    w = await start_worker(bus)
    # load (no prompt, stream False)
    resp = await client.post("/ollama/api/generate", json={
        "model": "m1", "stream": False})
    body = await resp.json()
    assert body["done"] is True and body["response"] == ""
    assert "done_reason" not in body
    # unload (keep_alive 0)
    resp = await client.post("/ollama/api/generate", json={
        "model": "m1", "keep_alive": 0, "stream": False})
    body = await resp.json()
    assert body["done_reason"] == "unload"
    await teardown(client, bus, registry, scheduler, w)


async def test_generate_validation_errors():
    client, bus, registry, scheduler = await make_client()
    w = await start_worker(bus)
    resp = await client.post("/ollama/api/generate", json={"prompt": "no model"})
    assert resp.status == 400
    body = await resp.json()
    assert "error" in body and "model" in body["error"]["message"]

    resp = await client.post("/ollama/api/generate", json={
        "model": "nope", "prompt": "x"})
    assert resp.status == 404

    resp = await client.post("/ollama/api/generate", json={
        "model": "m1", "prompt": "x" * (100 * 1024 + 1)})
    assert resp.status == 400
    await teardown(client, bus, registry, scheduler, w)


async def test_chat_keeps_structured_messages():
    """The §2.8 fix: /api/chat must deliver structured messages to the worker."""
    client, bus, registry, scheduler = await make_client()
    seen = {}

    class SpyWorker(FakeWorker):
        async def _execute(self, assignment):
            seen["messages"] = assignment.request.messages
            seen["requestType"] = assignment.request.metadata.get("requestType")
            await super()._execute(assignment)

    w = SpyWorker(bus, "w1", ["m1"], reply="hi there")
    await w.start()
    await bus.flush()
    msgs = [{"role": "system", "content": "be nice"},
            {"role": "user", "content": "hello"}]
    resp = await client.post("/ollama/api/chat", json={
        "model": "m1", "messages": msgs, "stream": False})
    body = await resp.json()
    assert resp.status == 200
    assert body["message"]["role"] == "assistant"
    assert seen["messages"] == msgs
    assert seen["requestType"] == "chat"
    await teardown(client, bus, registry, scheduler, w)


async def test_tags_aggregation():
    client, bus, registry, scheduler = await make_client()
    w1 = await start_worker(bus, worker_id="w1", models=["alpha", "beta"])
    w2 = await start_worker(bus, worker_id="w2", models=["alpha"])
    resp = await client.get("/ollama/api/tags")
    body = await resp.json()
    models = {m["name"]: m for m in body["models"]}
    assert models["alpha"]["gridllm_metadata"]["num_workers_with_model"] == 2
    assert models["beta"]["gridllm_metadata"]["num_workers_with_model"] == 1
    assert [m["name"] for m in body["models"]] == sorted(models)
    await teardown(client, bus, registry, scheduler, w1, w2)


async def test_openai_chat_completions():
    client, bus, registry, scheduler = await make_client()
    w = await start_worker(bus, reply="chat reply")
    resp = await client.post("/v1/chat/completions", json={
        "model": "m1", "messages": [{"role": "user", "content": "hi"}]})
    body = await resp.json()
    assert resp.status == 200
    assert body["object"] == "chat.completion"
    assert body["id"].startswith("chatcmpl-")
    assert body["choices"][0]["message"]["role"] == "assistant"
    assert body["choices"][0]["message"]["content"] == "chat reply"
    assert set(body["usage"]) == {"prompt_tokens", "completion_tokens", "total_tokens"}
    await teardown(client, bus, registry, scheduler, w)


async def test_openai_chat_streaming_sse():
    client, bus, registry, scheduler = await make_client()
    toks = ["he", "llo"]
    w = await start_worker(bus, stream_tokens=toks)
    resp = await client.post("/v1/chat/completions", json={
        "model": "m1", "messages": [{"role": "user", "content": "hi"}],
        "stream": True,
        "stream_options": {"include_usage": True}})
    assert resp.status == 200
    assert "text/event-stream" in resp.headers["Content-Type"]
    text = await resp.text()
    events = [l[len("data: "):] for l in text.split("\n") if l.startswith("data: ")]
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
    deltas = [c["choices"][0]["delta"].get("content", "") for c in chunks[:-1]]
    assert "".join(deltas) == "hello"
    assert chunks[-1]["choices"][0]["finish_reason"] == "stop"
    assert "usage" in chunks[-1]
    await teardown(client, bus, registry, scheduler, w)


async def test_openai_completions_echo():
    client, bus, registry, scheduler = await make_client()
    w = await start_worker(bus, reply=" world")
    resp = await client.post("/v1/completions", json={
        "model": "m1", "prompt": "hello", "echo": True})
    body = await resp.json()
    assert body["object"] == "text_completion"
    assert body["choices"][0]["text"] == "hello world"
    await teardown(client, bus, registry, scheduler, w)


async def test_openai_models_list():
    client, bus, registry, scheduler = await make_client()
    w = await start_worker(bus, models=["zeta", "alpha"])
    resp = await client.get("/v1/models")
    body = await resp.json()
    assert body["object"] == "list"
    assert [m["id"] for m in body["data"]] == ["alpha", "zeta"]
    assert all(m["object"] == "model" and m["owned_by"] == "gridllm"
               for m in body["data"])
    await teardown(client, bus, registry, scheduler, w)


async def test_embeddings_paths():
    client, bus, registry, scheduler = await make_client()

    class EmbedWorker(FakeWorker):
        async def _execute(self, assignment):
            from gridllm_tpu.utils.types import InferenceResponse, JobResult

            req = assignment.request
            inputs = req.input if isinstance(req.input, list) else [req.input]
            resp = InferenceResponse(
                id=assignment.jobId, model=req.model,
                embeddings=[[0.1, 0.2, 0.3] for _ in inputs],
                prompt_eval_count=len(inputs), done=True)
            result = JobResult(jobId=assignment.jobId, workerId=self.worker_id,
                               success=True, response=resp)
            await self.bus.publish("job:completed", result.model_dump_json())
            await self.bus.publish(f"job:result:{assignment.jobId}",
                                   result.model_dump_json())

    w = EmbedWorker(bus, "w1", ["emb"])
    await w.start()
    await bus.flush()
    resp = await client.post("/ollama/api/embed", json={
        "model": "emb", "input": ["a", "b"]})
    body = await resp.json()
    assert len(body["embeddings"]) == 2
    # legacy single-embedding shape
    resp = await client.post("/ollama/api/embeddings", json={
        "model": "emb", "prompt": "a"})
    body = await resp.json()
    assert body["embedding"] == [0.1, 0.2, 0.3]
    await teardown(client, bus, registry, scheduler, w)


async def test_inference_routes():
    client, bus, registry, scheduler = await make_client()
    w = await start_worker(bus)
    resp = await client.post("/inference", json={"model": "m1", "prompt": "x"})
    body = await resp.json()
    assert resp.status == 200 and body["done"] is True
    assert body["worker"] == "w1"

    resp = await client.get("/inference/models")
    body = await resp.json()
    assert body["models"][0]["name"] == "m1"

    resp = await client.get("/inference/queue")
    body = await resp.json()
    assert body["queue"]["totalProcessed"] == 1

    resp = await client.get("/inference/unknown-id/status")
    assert resp.status == 404
    await teardown(client, bus, registry, scheduler, w)


async def test_health_routes():
    client, bus, registry, scheduler = await make_client()
    for path, expected in [("/health", 200), ("/health/live", 200),
                           ("/health/ready", 200), ("/health/system", 200),
                           ("/health/workers", 200), ("/health/jobs", 200)]:
        resp = await client.get(path)
        assert resp.status == expected, path
    body = await (await client.get("/health/ready")).json()
    assert body["status"] == "ready"
    await teardown(client, bus, registry, scheduler)


async def test_404_envelope():
    client, bus, registry, scheduler = await make_client()
    resp = await client.get("/nope")
    assert resp.status == 404
    body = await resp.json()
    assert body["error"]["code"] == "NOT_FOUND"
    assert body["path"] == "/nope"
    await teardown(client, bus, registry, scheduler)


async def test_rate_limit():
    client, bus, registry, scheduler = await make_client(rate_limit=3)
    for i in range(3):
        resp = await client.get("/")
        assert resp.status == 200
        assert resp.headers["X-RateLimit-Remaining"] == str(2 - i)
    resp = await client.get("/")
    assert resp.status == 429
    assert "Retry-After" in resp.headers
    # health bypassed
    resp = await client.get("/health")
    assert resp.status == 200
    await teardown(client, bus, registry, scheduler)


async def test_api_version_and_ps():
    client, bus, registry, scheduler = await make_client()
    w = await start_worker(bus)
    resp = await client.get("/ollama/api/version")
    assert "version" in await resp.json()
    # bare mount too
    resp = await client.get("/api/version")
    assert resp.status == 200
    resp = await client.get("/api/ps")
    body = await resp.json()
    assert body["models"][0]["name"] == "m1"
    # /api/pull is real now (model management); /api/push has no remote
    # registry to push to and stays 501
    resp = await client.post("/api/push", json={"model": "m1"})
    assert resp.status == 501
    await teardown(client, bus, registry, scheduler, w)


async def test_openai_streaming_failure_delivers_error_frame():
    """A permanently failed job must surface as an SSE error, not a clean
    completion."""
    client, bus, registry, scheduler = await make_client()
    w = await start_worker(bus, fail_times=99)
    resp = await client.post("/v1/chat/completions", json={
        "model": "m1", "messages": [{"role": "user", "content": "hi"}],
        "stream": True})
    text = await resp.text()
    events = [l[len("data: "):] for l in text.split("\n") if l.startswith("data: ")]
    assert events[-1] == "[DONE]"
    parsed = [json.loads(e) for e in events[:-1]]
    assert any("error" in p for p in parsed), f"no error frame in {parsed}"
    assert not any(p.get("choices", [{}])[0].get("finish_reason") == "stop"
                   for p in parsed)
    await teardown(client, bus, registry, scheduler, w)


async def test_malformed_field_types_return_400():
    client, bus, registry, scheduler = await make_client()
    w = await start_worker(bus, models=["m1", "emb"])
    # options as a string → pydantic rejects → 400 not 500
    resp = await client.post("/ollama/api/generate", json={
        "model": "m1", "prompt": "x", "options": "bad", "stream": False})
    assert resp.status == 400
    body = await resp.json()
    assert body["error"]["code"] == "VALIDATION_ERROR"
    # embed with numeric input
    resp = await client.post("/ollama/api/embed", json={"model": "emb", "input": 123})
    assert resp.status == 400
    await teardown(client, bus, registry, scheduler, w)
