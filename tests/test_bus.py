"""Bus contract tests (in-memory implementation).

Covers the §2.6 protocol primitives the scheduler/worker rely on: KV with
TTL (heartbeat keys), hashes (`workers`, `active_jobs`), pub/sub channels,
pattern subscribe, and the subscribe-handle unsubscribe semantics that fix
the reference's listener leak (SURVEY.md §2.8).
"""

import asyncio

import pytest

from gridllm_tpu.bus import InMemoryBus


@pytest.fixture
def bus():
    b = InMemoryBus(key_prefix="T:")
    asyncio.run(b.connect())
    return b


async def test_kv_roundtrip(bus):
    await bus.set("k", "v")
    assert await bus.get("k") == "v"
    # prefix applied internally
    assert bus._kv.get("T:k") == "v"
    await bus.delete("k")
    assert await bus.get("k") is None


async def test_ttl_semantics(bus):
    assert await bus.ttl("missing") == -2
    await bus.set("plain", "x")
    assert await bus.ttl("plain") == -1
    await bus.set_with_expiry("hb", "alive", ttl_s=5)
    assert 0 <= await bus.ttl("hb") <= 5
    await bus.set_with_expiry("gone", "x", ttl_s=0.01)
    await asyncio.sleep(0.02)
    assert await bus.get("gone") is None
    assert await bus.ttl("gone") == -2


async def test_hash_ops(bus):
    await bus.hset("workers", "w1", "{}")
    await bus.hset("workers", "w2", "{...}")
    assert await bus.hget("workers", "w1") == "{}"
    assert set((await bus.hgetall("workers")).keys()) == {"w1", "w2"}
    await bus.hdel("workers", "w1")
    assert await bus.hget("workers", "w1") is None


async def test_pubsub_and_unsubscribe(bus):
    got: list[tuple[str, str]] = []

    async def handler(ch, msg):
        got.append((ch, msg))

    sub = await bus.subscribe("job:completed", handler)
    n = await bus.publish("job:completed", "a")
    await bus.flush()
    assert n == 1 and got == [("job:completed", "a")]

    # unsubscribe removes exactly this handler (no listener leak)
    await sub.unsubscribe()
    await bus.publish("job:completed", "b")
    await bus.flush()
    assert got == [("job:completed", "a")]


async def test_two_handlers_same_channel(bus):
    got1, got2 = [], []

    async def h1(ch, m):
        got1.append(m)

    async def h2(ch, m):
        got2.append(m)

    s1 = await bus.subscribe("c", h1)
    await bus.subscribe("c", h2)
    await bus.publish("c", "x")
    await bus.flush()
    assert got1 == ["x"] and got2 == ["x"]
    await s1.unsubscribe()
    await bus.publish("c", "y")
    await bus.flush()
    assert got1 == ["x"] and got2 == ["x", "y"]


async def test_psubscribe(bus):
    got = []

    async def handler(ch, m):
        got.append((ch, m))

    sub = await bus.psubscribe("worker:*:job", handler)
    await bus.publish("worker:w1:job", "assign")
    await bus.publish("other:w1:job", "no")
    await bus.flush()
    assert got == [("worker:w1:job", "assign")]
    await sub.unsubscribe()


async def test_handler_error_does_not_break_bus(bus):
    ok = []

    async def bad(ch, m):
        raise RuntimeError("boom")

    async def good(ch, m):
        ok.append(m)

    await bus.subscribe("c", bad)
    await bus.subscribe("c", good)
    await bus.publish("c", "m")
    await bus.flush()
    assert ok == ["m"]


async def test_per_subscriber_ordering(bus):
    """A slow handler must still see frames in publish order (token streams)."""
    import random

    got = []

    async def slow(ch, m):
        await asyncio.sleep(random.uniform(0, 0.003))
        got.append(m)

    await bus.subscribe("job:stream:x", slow)
    for i in range(20):
        await bus.publish("job:stream:x", str(i))
    await bus.flush()
    assert got == [str(i) for i in range(20)]
