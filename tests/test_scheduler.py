"""Scheduler policy unit tests against the in-memory fake bus + fake workers
(SURVEY.md §4): selection, priority, retries, orphan promotion, liveness,
crash recovery — the behaviors inventoried from JobScheduler.ts/WorkerRegistry.ts."""

import asyncio
import json
import uuid

import pytest

from gridllm_tpu.bus import InMemoryBus
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.scheduler.scheduler import JobTimeoutError
from gridllm_tpu.utils.types import InferenceRequest, Priority

from .helpers import FakeWorker, fast_config


def req(model="m1", priority=Priority.medium, **kw) -> InferenceRequest:
    return InferenceRequest(id=f"job-{uuid.uuid4().hex[:8]}", model=model,
                            prompt="hi", priority=priority, **kw)


async def make_stack():
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    cfg = fast_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    return bus, registry, scheduler


async def teardown(bus, registry, scheduler, *workers):
    for w in workers:
        await w.stop(announce=False)
    await scheduler.shutdown()
    await registry.shutdown()
    await bus.disconnect()


async def test_register_and_complete_job():
    bus, registry, scheduler = await make_stack()
    w = FakeWorker(bus, "w1", ["m1"])
    await w.start()
    await bus.flush()
    assert registry.get_worker("w1") is not None

    result = await scheduler.submit_and_wait(req(), timeout_ms=3000)
    assert result.success and result.response.response == "canned response"
    assert scheduler.get_stats()["activeJobs"] == 0
    # worker freed again
    assert registry.get_worker("w1").currentJobs == 0
    assert registry.get_worker("w1").totalJobsProcessed == 1
    await teardown(bus, registry, scheduler, w)


async def test_least_loaded_selection():
    bus, registry, scheduler = await make_stack()
    w1 = FakeWorker(bus, "w1", ["m1"], max_concurrent=4, delay_s=0.3)
    w2 = FakeWorker(bus, "w2", ["m1"], max_concurrent=4, delay_s=0.3)
    await w1.start()
    await w2.start()
    await bus.flush()

    results = await asyncio.gather(
        *[scheduler.submit_and_wait(req(), timeout_ms=4000) for _ in range(4)])
    assert all(r.success for r in results)
    # least-loaded spread: both workers got work
    assert len(w1.processed) == 2 and len(w2.processed) == 2
    await teardown(bus, registry, scheduler, w1, w2)


async def test_model_routing():
    bus, registry, scheduler = await make_stack()
    w1 = FakeWorker(bus, "w1", ["llama"], reply="from-llama")
    w2 = FakeWorker(bus, "w2", ["mixtral"], reply="from-mixtral")
    await w1.start()
    await w2.start()
    await bus.flush()

    r1 = await scheduler.submit_and_wait(req(model="llama"), timeout_ms=3000)
    r2 = await scheduler.submit_and_wait(req(model="mixtral"), timeout_ms=3000)
    assert r1.response.response == "from-llama"
    assert r2.response.response == "from-mixtral"
    await teardown(bus, registry, scheduler, w1, w2)


async def test_priority_ordering():
    """With one single-slot worker busy, a later high-priority job must run
    before earlier low-priority jobs."""
    bus, registry, scheduler = await make_stack()
    w = FakeWorker(bus, "w1", ["m1"], delay_s=0.15)
    await w.start()
    await bus.flush()

    order = []

    async def submit(r):
        res = await scheduler.submit_and_wait(r, timeout_ms=8000)
        order.append(r.id)
        return res

    blocker = asyncio.ensure_future(submit(req()))
    await asyncio.sleep(0.05)  # blocker assigned; queue empty
    low1, low2, high = req(priority=Priority.low), req(priority=Priority.low), req(priority=Priority.high)
    tasks = [asyncio.ensure_future(submit(low1)), asyncio.ensure_future(submit(low2))]
    await asyncio.sleep(0.01)
    tasks.append(asyncio.ensure_future(submit(high)))
    await asyncio.gather(blocker, *tasks)
    assert order[1] == high.id, f"high-priority job should run first after blocker, got {order}"
    await teardown(bus, registry, scheduler, w)


async def test_job_queued_until_model_owner_appears():
    bus, registry, scheduler = await make_stack()
    fut = asyncio.ensure_future(scheduler.submit_and_wait(req(model="late"), timeout_ms=5000))
    await asyncio.sleep(0.2)
    assert scheduler.get_stats()["queuedJobs"] == 1
    w = FakeWorker(bus, "w1", ["late"])
    await w.start()
    result = await fut
    assert result.success
    await teardown(bus, registry, scheduler, w)


async def test_retry_then_success_transparent_to_waiter():
    """Failures below the retry limit are invisible to the waiter."""
    bus, registry, scheduler = await make_stack()
    w = FakeWorker(bus, "w1", ["m1"], fail_times=2)  # retry_attempts=2
    await w.start()
    await bus.flush()
    result = await scheduler.submit_and_wait(req(), timeout_ms=5000)
    assert result.success
    assert result.response.response == "canned response"
    await teardown(bus, registry, scheduler, w)


async def test_retries_exhausted_delivers_error():
    bus, registry, scheduler = await make_stack()
    w = FakeWorker(bus, "w1", ["m1"], fail_times=99)
    await w.start()
    await bus.flush()
    result = await scheduler.submit_and_wait(req(), timeout_ms=5000)
    assert not result.success
    assert "injected failure" in result.error
    r = req()
    r.metadata["retryCount"] = 0
    assert scheduler.total_failed >= 1
    await teardown(bus, registry, scheduler, w)


async def test_orphan_on_worker_death_reassigned():
    """Kill a worker mid-job: the job is promoted to high priority, requeued
    at the front, and completed by a surviving worker — transparently."""
    bus, registry, scheduler = await make_stack()
    slow = FakeWorker(bus, "doomed", ["m1"], delay_s=10)
    await slow.start()
    await bus.flush()

    fut = asyncio.ensure_future(scheduler.submit_and_wait(req(), timeout_ms=8000))
    await asyncio.sleep(0.1)
    assert scheduler.get_stats()["activeJobs"] == 1
    await slow.die()  # abrupt: no unregister, heartbeat TTL gone

    # registry notices via aliveness probe / cleanup; scheduler orphans
    backup = FakeWorker(bus, "backup", ["m1"], reply="rescued")
    await backup.start()
    result = await asyncio.wait_for(fut, 8)
    assert result.success and result.response.response == "rescued"
    assert result.workerId == "backup"
    # audit metadata recorded on the requeued request path
    await teardown(bus, registry, scheduler, slow, backup)


async def test_orphan_metadata_recorded():
    bus, registry, scheduler = await make_stack()
    slow = FakeWorker(bus, "doomed", ["m1"], delay_s=10)
    await slow.start()
    await bus.flush()
    orphaned = []
    scheduler.on("job_orphaned", lambda r: orphaned.append(r))
    fut = asyncio.ensure_future(scheduler.submit_and_wait(req(), timeout_ms=6000))
    await asyncio.sleep(0.1)
    await slow.die()
    await asyncio.sleep(1.0)
    assert len(orphaned) == 1
    r = orphaned[0]
    assert r.metadata["orphaned"] is True
    assert r.metadata["originalWorkerId"] == "doomed"
    assert r.metadata["requeueCount"] == 1
    assert r.priority == Priority.high
    fut.cancel()
    await teardown(bus, registry, scheduler, slow)


async def test_graceful_unregister_removes_worker():
    bus, registry, scheduler = await make_stack()
    w = FakeWorker(bus, "w1", ["m1"])
    await w.start()
    await bus.flush()
    assert registry.get_worker("w1") is not None
    await w.stop(announce=True)
    await bus.flush()
    assert registry.get_worker("w1") is None
    await teardown(bus, registry, scheduler)


async def test_heartbeat_timeout_eviction():
    bus, registry, scheduler = await make_stack()
    w = FakeWorker(bus, "w1", ["m1"])
    await w.start()
    await bus.flush()
    # stop heartbeating without announcing; TTL key expires (0.4s)
    await w.stop(announce=False)
    await bus.delete("heartbeat:w1")
    await asyncio.sleep(1.0)  # heartbeat timeout 0.6s + cleanup 0.1s
    assert registry.get_worker("w1") is None
    await teardown(bus, registry, scheduler)


async def test_unknown_heartbeat_triggers_reregistration():
    bus, registry, scheduler = await make_stack()
    w = FakeWorker(bus, "ghost", ["m1"])
    # heartbeat without registering or bus record
    await w.bus.publish("worker:heartbeat", json.dumps(
        {"workerId": "ghost", "status": "online", "currentJobs": 0}))
    reregister_requests = []

    async def spy(ch, m):
        reregister_requests.append(m)

    await bus.subscribe("worker:reregister:ghost", spy)
    await bus.publish("worker:heartbeat", json.dumps(
        {"workerId": "ghost", "status": "online", "currentJobs": 0}))
    await bus.flush()
    assert len(reregister_requests) >= 1
    await teardown(bus, registry, scheduler)


async def test_submit_timeout_and_cancellation():
    bus, registry, scheduler = await make_stack()
    w = FakeWorker(bus, "w1", ["m1"], delay_s=10)
    await w.start()
    await bus.flush()
    with pytest.raises(JobTimeoutError):
        await scheduler.submit_and_wait(req(), timeout_ms=300)
    await asyncio.sleep(0.05)
    assert scheduler.get_stats()["activeJobs"] == 0
    assert len(w.cancelled) == 1  # worker received job_cancellation
    await teardown(bus, registry, scheduler, w)


async def test_streaming_job_chunks_in_order():
    bus, registry, scheduler = await make_stack()
    toks = [f"t{i} " for i in range(10)]
    w = FakeWorker(bus, "w1", ["m1"], stream_tokens=toks)
    await w.start()
    await bus.flush()
    got = []

    async def on_chunk(chunk):
        got.append(chunk.response)

    r = req(stream=True)
    result = await scheduler.submit_streaming_job(r, on_chunk, timeout_ms=5000)
    assert result.success
    assert got == toks
    assert result.response.response == "".join(toks)
    await teardown(bus, registry, scheduler, w)


async def test_crash_recovery_reload_from_bus():
    """Server restart: queued + active jobs and workers reload from the bus
    (reference: loadExistingJobs/loadExistingWorkers)."""
    bus, registry, scheduler = await make_stack()
    w = FakeWorker(bus, "w1", ["m1"], delay_s=0.4)
    await w.start()
    await bus.flush()
    # one active + two queued (worker has 1 slot)
    fut1 = asyncio.ensure_future(scheduler.submit_and_wait(req(), timeout_ms=8000))
    await asyncio.sleep(0.1)
    q1, q2 = req(), req()
    await scheduler.add_job(q1)
    await scheduler.add_job(q2)

    # "crash": drop in-memory state, build a new registry+scheduler on same bus
    await scheduler.shutdown()
    await registry.shutdown()
    cfg = fast_config()
    registry2 = WorkerRegistry(bus, cfg)
    scheduler2 = JobScheduler(bus, registry2, cfg)
    await registry2.initialize()
    await scheduler2.initialize()
    assert registry2.get_worker("w1") is not None
    # both queued jobs recovered, eventually processed
    await asyncio.sleep(2.0)
    assert {q1.id, q2.id} <= set(w.processed)
    fut1.cancel()
    await teardown(bus, registry2, scheduler2, w)


async def test_cancel_during_retry_window():
    """A job failed into its retry-delay window must be cancellable (no
    zombie resurrection)."""
    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    cfg = fast_config()
    cfg = cfg.model_copy(update={"retry_delay_ms": 1_000})  # wide retry window
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    w = FakeWorker(bus, "w1", ["m1"], fail_times=99)
    await w.start()
    await bus.flush()
    r = req()
    await scheduler.add_job(r)
    await asyncio.sleep(0.2)  # first failure landed; job sits in retry window
    assert r.id in scheduler._retry_handles
    assert await scheduler.cancel_job(r.id) is True
    failures_before = w.fail_times
    await asyncio.sleep(1.2)
    assert w.fail_times == failures_before  # never resurrected
    await teardown(bus, registry, scheduler, w)


async def test_heartbeat_does_not_erase_busy_accounting():
    """A stale heartbeat self-reporting idle must not reopen a full worker."""
    bus, registry, scheduler = await make_stack()
    w = FakeWorker(bus, "w1", ["m1"], delay_s=0.5)
    await w.start()
    await bus.flush()
    fut = asyncio.ensure_future(scheduler.submit_and_wait(req(), timeout_ms=5000))
    await asyncio.sleep(0.1)
    info = registry.get_worker("w1")
    assert info.currentJobs == 1 and info.status == "busy"
    # stale heartbeat claims idle
    await bus.publish("worker:heartbeat", json.dumps(
        {"workerId": "w1", "status": "online", "currentJobs": 0}))
    await bus.flush()
    info = registry.get_worker("w1")
    assert info.currentJobs == 1, "registry accounting must be authoritative"
    assert registry.get_available_workers_by_model("m1") == []
    await fut
    await teardown(bus, registry, scheduler, w)


async def test_non_retryable_failure_fails_fast():
    """retryable=False on job:failed skips the retry ladder entirely —
    the waiter gets the error after ONE attempt (permanent errors like
    generation-on-embedding-model must not burn retry delays)."""
    bus, registry, scheduler = await make_stack()
    w = FakeWorker(bus, "w1", ["m1"], fail_times=99, fail_retryable=False)
    await w.start()
    await bus.flush()
    t0 = asyncio.get_running_loop().time()
    result = await scheduler.submit_and_wait(req(), timeout_ms=5000)
    elapsed = asyncio.get_running_loop().time() - t0
    assert not result.success and "injected failure" in result.error
    assert w.fail_times == 98  # exactly one attempt
    assert elapsed < 2.0       # no retry delays burned
    await teardown(bus, registry, scheduler, w)


async def test_nack_does_not_consume_retry_ladder():
    """VERDICT #8: a capacity NACK requeues without retryCount++ — more
    NACKs than retry_attempts must still end in success once capacity
    frees (the reference burned a retry per NACK; 3 races = permafail)."""
    bus, registry, scheduler = await make_stack()
    # 5 NACKs > retry_attempts=2, then the worker accepts
    w = FakeWorker(bus, "w1", ["m1"], nack_times=5)
    await w.start()
    await bus.flush()

    result = await scheduler.submit_and_wait(req(), timeout_ms=5000)
    assert result.success
    assert scheduler.total_failed == 0
    await teardown(bus, registry, scheduler, w)


async def test_layout_tiebreak_discriminates():
    """VERDICT #8: the shard-layout tiebreak must distinguish workers.
    (a) context fit: a request with num_ctx beyond one worker's layout
    routes to the worker whose layout can hold it; (b) slot headroom:
    at equal load, the layout with more batch slots wins."""
    from gridllm_tpu.utils.types import ModelShardLayout

    bus, registry, scheduler = await make_stack()
    small = FakeWorker(bus, "small", ["m1"], layouts=[
        ModelShardLayout(name="m1", maxSeqLen=512, maxBatchSlots=4)])
    big = FakeWorker(bus, "big", ["m1"], layouts=[
        ModelShardLayout(name="m1", strategy="tensor",
                         meshAxes={"tp": 8}, maxSeqLen=8192,
                         maxBatchSlots=16)])
    await small.start()
    await big.start()
    await bus.flush()

    # (a) long-context request → only `big`'s layout fits
    r = await scheduler.submit_and_wait(
        req(options={"num_ctx": 4096}), timeout_ms=3000)
    assert r.success and r.workerId == "big"
    # (b) no ctx hint, equal load → more slot headroom wins
    r = await scheduler.submit_and_wait(req(), timeout_ms=3000)
    assert r.success and r.workerId == "big"
    await teardown(bus, registry, scheduler, small, big)
