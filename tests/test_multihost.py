"""Multi-host worker-group lifecycle (VERDICT.md #4 / SURVEY.md §5.8b).

Two real processes × 4 virtual CPU devices form one jax slice (8 global
devices), prove a cross-process collective, register ONE logical worker on
a real RESP broker, then the test kills the follower mid-flight and asserts
the liaison fails the WHOLE logical worker: `worker:disconnected` published
(the scheduler's orphan trigger) and the registry entry removed.
"""

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
from pathlib import Path

import pytest

from gridllm_tpu.bus import create_bus
from gridllm_tpu.bus.broker import GridBusBroker

CHILD = Path(__file__).with_name("multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def test_slice_failure_fails_logical_worker():
    broker = GridBusBroker()
    await broker.start(port=0)
    coord_port = _free_port()
    worker_id = "slice-w1"

    env = {**os.environ, "PYTHONPATH": str(CHILD.parent.parent)}
    # children pin their own platform config; scrub this process's test env
    env.pop("XLA_FLAGS", None)

    def spawn(pid: int) -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, str(CHILD), str(pid), str(coord_port),
             str(broker.port), worker_id],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )

    liaison = spawn(0)
    follower = spawn(1)

    bus = create_bus(f"resp://127.0.0.1:{broker.port}", key_prefix="T:")
    await bus.connect()
    disconnected = asyncio.Event()
    payloads: list[dict] = []

    async def on_disc(_ch: str, raw: str) -> None:
        payloads.append(json.loads(raw))
        disconnected.set()

    sub = await bus.subscribe("worker:disconnected", on_disc)

    try:
        # wait for the logical worker to register (one entry, liaison-owned)
        for _ in range(600):
            if await bus.hget("workers", worker_id):
                break
            await asyncio.sleep(0.1)
        else:
            out = liaison.communicate(timeout=5)[0] if liaison.poll() is not None else ""
            pytest.fail(f"logical worker never registered; liaison said: {out}")

        workers = await bus.hgetall("workers")
        assert list(workers) == [worker_id]  # ONE logical worker, not two

        # kill the follower abruptly — no clean shutdown, TTL must expire
        follower.send_signal(signal.SIGKILL)
        await asyncio.wait_for(disconnected.wait(), timeout=30)
        assert payloads and payloads[0]["workerId"] == worker_id
        assert "slice members lost" in payloads[0]["reason"]
        # registry entry gone → scheduler orphan path takes over from here
        # (hdel lands just after the publish — poll briefly)
        for _ in range(100):
            if await bus.hget("workers", worker_id) is None:
                break
            await asyncio.sleep(0.05)
        assert await bus.hget("workers", worker_id) is None

        liaison.wait(timeout=30)
        assert liaison.returncode == 0, liaison.communicate()[0]
    finally:
        for p in (liaison, follower):
            if p.poll() is None:
                p.kill()
        await sub.unsubscribe()
        await bus.disconnect()
        await broker.stop()
