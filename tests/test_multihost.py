"""Multi-host worker-group lifecycle (VERDICT.md #4 / SURVEY.md §5.8b).

Two real processes × 4 virtual CPU devices form one jax slice (8 global
devices), prove a cross-process collective, register ONE logical worker on
a real RESP broker, then the test kills the follower mid-flight and asserts
the liaison fails the WHOLE logical worker: `worker:disconnected` published
(the scheduler's orphan trigger) and the registry entry removed.
"""

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
from pathlib import Path

import pytest

from gridllm_tpu.bus import create_bus
from gridllm_tpu.bus.broker import GridBusBroker

CHILD = Path(__file__).with_name("multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def test_slice_failure_fails_logical_worker():
    broker = GridBusBroker()
    await broker.start(port=0)
    coord_port = _free_port()
    worker_id = "slice-w1"

    env = {**os.environ, "PYTHONPATH": str(CHILD.parent.parent)}
    # children pin their own platform config; scrub this process's test env
    env.pop("XLA_FLAGS", None)

    def spawn(pid: int) -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, str(CHILD), str(pid), str(coord_port),
             str(broker.port), worker_id],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )

    liaison = spawn(0)
    follower = spawn(1)

    bus = create_bus(f"resp://127.0.0.1:{broker.port}", key_prefix="T:")
    await bus.connect()
    disconnected = asyncio.Event()
    payloads: list[dict] = []

    async def on_disc(_ch: str, raw: str) -> None:
        payloads.append(json.loads(raw))
        disconnected.set()

    sub = await bus.subscribe("worker:disconnected", on_disc)

    try:
        # wait for the logical worker to register (one entry, liaison-owned)
        # (generous: under full-suite CPU contention the 2-process jax
        # group init + first compiles can take well over a minute)
        for _ in range(1200):
            if await bus.hget("workers", worker_id):
                break
            await asyncio.sleep(0.1)
        else:
            out = liaison.communicate(timeout=5)[0] if liaison.poll() is not None else ""
            pytest.fail(f"logical worker never registered; liaison said: {out}")

        workers = await bus.hgetall("workers")
        assert list(workers) == [worker_id]  # ONE logical worker, not two

        # kill the follower abruptly — no clean shutdown, TTL must expire
        follower.send_signal(signal.SIGKILL)
        await asyncio.wait_for(disconnected.wait(), timeout=60)
        assert payloads and payloads[0]["workerId"] == worker_id
        assert "slice members lost" in payloads[0]["reason"]
        # registry entry gone → scheduler orphan path takes over from here
        # (hdel lands just after the publish — poll briefly)
        for _ in range(100):
            if await bus.hget("workers", worker_id) is None:
                break
            await asyncio.sleep(0.05)
        assert await bus.hget("workers", worker_id) is None

        liaison.wait(timeout=30)
        assert liaison.returncode == 0, liaison.communicate()[0]
    finally:
        for p in (liaison, follower):
            if p.poll() is None:
                p.kill()
        await sub.unsubscribe()
        await bus.disconnect()
        await broker.stop()


SERVE_CHILD = Path(__file__).with_name("multihost_serve_child.py")


async def test_multihost_slice_serves_generate():
    """VERDICT r03 missing #1 upgraded from 'psum works' to 'inference
    works': a 2-process × 4-CPU-device slice (tp=8 — wq/wo genuinely
    sharded across BOTH processes) serves a real /ollama/api/generate
    through gateway + scheduler + bus, with the follower replaying the
    liaison's step plan (worker/plan.py) so every process issues the same
    SPMD computations."""
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.gateway.app import create_app
    from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
    from gridllm_tpu.utils.config import Config

    broker = GridBusBroker()
    await broker.start(port=0)
    coord_port = _free_port()
    worker_id = "slice-serve-w1"

    env = {**os.environ, "PYTHONPATH": str(CHILD.parent.parent)}
    env.pop("XLA_FLAGS", None)

    import tempfile

    logdir = tempfile.mkdtemp(prefix="mh_serve_")
    logs = {}

    def spawn(pid: int) -> subprocess.Popen:
        # log to FILES, not pipes: an undrained pipe fills its ~64KB buffer
        # and blocks the child mid-serving, hanging the test instead of
        # failing it with diagnostics
        logs[pid] = open(os.path.join(logdir, f"child{pid}.log"), "w+")
        return subprocess.Popen(
            [sys.executable, str(SERVE_CHILD), str(pid), str(coord_port),
             str(broker.port), worker_id, str(_free_port())],
            env=env, stdout=logs[pid], stderr=subprocess.STDOUT,
            text=True,
        )

    liaison = spawn(0)
    follower = spawn(1)

    bus = create_bus(f"resp://127.0.0.1:{broker.port}")
    await bus.connect()
    config = Config()
    registry = WorkerRegistry(bus, config.scheduler)
    scheduler = JobScheduler(bus, registry, config.scheduler)
    await registry.initialize()
    await scheduler.initialize()
    app = create_app(bus, registry, scheduler, config)
    client = TestClient(TestServer(app))
    await client.start_server()

    try:
        # the logical worker registers once engines are built on BOTH
        # processes and the slice's jit programs are ready to serve
        for _ in range(1200):  # CPU-mesh compiles are slow; be generous
            if registry.get_worker(worker_id) is not None:
                break
            await asyncio.sleep(0.1)
        else:
            logs[0].flush()
            logs[0].seek(0)
            pytest.fail("slice worker never registered; liaison: "
                        + logs[0].read()[-2000:])

        resp = await asyncio.wait_for(client.post("/ollama/api/generate", json={
            "model": "tiny-llama", "prompt": "hello slice", "stream": False,
            "options": {"temperature": 0, "num_predict": 6},
        }), timeout=120)
        body = await resp.json()
        assert resp.status == 200, body
        assert body["done"] is True
        assert body["eval_count"] == 6
        assert body["done_reason"] in ("stop", "length")

        # lockstep is what SUCCESS proves: tp=8 spans both processes, so a
        # non-replaying follower would deadlock the first collective and
        # the request would never complete. A second request asserts the
        # lockstep survives sustained serving (slot reuse, fresh admit).
        resp2 = await asyncio.wait_for(client.post("/ollama/api/generate", json={
            "model": "tiny-llama", "prompt": "again", "stream": False,
            "options": {"temperature": 0, "num_predict": 4},
        }), timeout=120)
        body2 = await resp2.json()
        assert resp2.status == 200 and body2["eval_count"] == 4
    finally:
        for p in (liaison, follower):
            if p.poll() is None:
                p.kill()
        for f in logs.values():
            f.close()
        await client.close()
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()
        await broker.stop()
