"""Full-slice e2e: gateway HTTP → scheduler → bus → REAL WorkerService →
InferenceEngine (tiny-llama, byte tokenizer) → streamed back.

This is the rebuild's "minimum end-to-end slice" milestone test
(SURVEY.md §7 step 4) — the reference's equivalent is the differential
integration harness (tests/integration/integration.ts) with Ollama swapped
for the TPU engine.
"""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from gridllm_tpu.bus.memory import InMemoryBus
from gridllm_tpu.engine import EngineConfig, InferenceEngine
from gridllm_tpu.gateway.app import create_app
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.config import Config, WorkerConfig
from gridllm_tpu.utils.types import WorkerInfo
from gridllm_tpu.worker.service import WorkerService
from tests.helpers import fast_config

MODEL = "tiny-llama"


@pytest.fixture(scope="module")
def tiny_engine():
    return InferenceEngine(EngineConfig(
        model=MODEL, max_slots=4, page_size=8, num_pages=64,
        max_pages_per_slot=8, prefill_buckets=(16, 32),
    ))


async def _stack(tiny_engine):
    bus = InMemoryBus()
    await bus.connect()
    sched_cfg = fast_config()
    registry = WorkerRegistry(bus, sched_cfg)
    scheduler = JobScheduler(bus, registry, sched_cfg)
    await registry.initialize()
    await scheduler.initialize()
    config = Config()
    config.scheduler = sched_cfg
    app = create_app(bus, registry, scheduler, config)
    worker = WorkerService(
        bus, {MODEL: tiny_engine},
        WorkerConfig(heartbeat_interval_ms=150, resource_monitor_interval_ms=500),
        stream_flush_ms=5,
    )
    await worker.start()
    await asyncio.sleep(0.05)  # registration propagation
    client = TestClient(TestServer(app))
    await client.start_server()
    return bus, registry, scheduler, worker, client


async def _teardown(registry, scheduler, worker, client, bus):
    await client.close()
    await worker.stop()
    await scheduler.shutdown()
    await registry.shutdown()
    await bus.disconnect()


async def test_full_slice_generate_chat_embed_stream(tiny_engine):
    bus, registry, scheduler, worker, client = await _stack(tiny_engine)
    try:
        # worker registered with capabilities incl. topology (new fields)
        workers = registry.get_all_workers()
        assert len(workers) == 1
        info: WorkerInfo = workers[0]
        assert info.capabilities.systemResources is not None
        assert info.capabilities.topology is not None
        assert info.capabilities.maxConcurrentTasks == 4

        # --- non-streaming generate
        resp = await client.post("/ollama/api/generate", json={
            "model": MODEL, "prompt": "hi", "stream": False,
            "options": {"temperature": 0, "num_predict": 6},
        })
        assert resp.status == 200
        body = await resp.json()
        assert body["model"] == MODEL and body["done"] is True
        assert body["eval_count"] == 6
        assert body["total_duration"] > 0 and body["eval_duration"] >= 0
        assert isinstance(body.get("context"), list) and body["context"]

        # --- streaming generate (NDJSON), chunks concatenate to final text
        resp = await client.post("/ollama/api/generate", json={
            "model": MODEL, "prompt": "stream me",
            "options": {"temperature": 0, "num_predict": 8},
        })
        assert resp.status == 200
        lines = [json.loads(l) for l in (await resp.text()).strip().splitlines()]
        assert lines[-1]["done"] is True
        streamed = "".join(l.get("response", "") for l in lines[:-1])
        # non-streamed equivalent must match (greedy determinism through the
        # whole distributed stack)
        resp2 = await client.post("/ollama/api/generate", json={
            "model": MODEL, "prompt": "stream me", "stream": False,
            "options": {"temperature": 0, "num_predict": 8},
        })
        assert streamed == (await resp2.json())["response"]

        # --- chat (structured messages path)
        resp = await client.post("/ollama/api/chat", json={
            "model": MODEL, "stream": False,
            "messages": [{"role": "user", "content": "hello there"}],
            "options": {"temperature": 0, "num_predict": 5},
        })
        assert resp.status == 200
        body = await resp.json()
        assert body["message"]["role"] == "assistant"
        assert body["eval_count"] == 5

        # --- embeddings
        resp = await client.post("/ollama/api/embed", json={
            "model": MODEL, "input": ["alpha", "beta"],
        })
        assert resp.status == 200
        body = await resp.json()
        assert len(body["embeddings"]) == 2
        assert len(body["embeddings"][0]) == 64

        # --- OpenAI chat completions over the same worker
        resp = await client.post("/v1/chat/completions", json={
            "model": MODEL, "stream": False,
            "messages": [{"role": "user", "content": "hey"}],
            "max_tokens": 4, "temperature": 0,
        })
        assert resp.status == 200
        body = await resp.json()
        assert body["choices"][0]["message"]["role"] == "assistant"
        assert body["usage"]["completion_tokens"] == 4

        # --- /api/tags aggregates engine-backed models
        resp = await client.get("/ollama/api/tags")
        names = [m["name"] for m in (await resp.json())["models"]]
        assert MODEL in names
    finally:
        await _teardown(registry, scheduler, worker, client, bus)


async def test_worker_nacks_over_capacity(tiny_engine):
    """Over-capacity assignment is NACKed (job:failed) instead of silently
    dropped (reference defect WorkerClientService.ts:500-505) and the
    scheduler retries it."""
    bus, registry, scheduler, worker, client = await _stack(tiny_engine)
    try:
        worker.max_concurrent = 0  # force: every assignment is over capacity
        resp = await client.post("/ollama/api/generate", json={
            "model": MODEL, "prompt": "x", "stream": False,
            "options": {"temperature": 0, "num_predict": 2},
        })
        # scheduler retries (fast_config: 2 attempts) then fails the job
        assert resp.status >= 500
    finally:
        await _teardown(registry, scheduler, worker, client, bus)


async def test_job_cancellation_mid_stream(tiny_engine):
    bus, registry, scheduler, worker, client = await _stack(tiny_engine)
    try:
        # long generation we cancel via DELETE /inference/{id}
        async with client.post("/ollama/api/generate", json={
            "model": MODEL, "prompt": "cancel me",
            "options": {"temperature": 0, "num_predict": -1},
        }) as resp:
            # read one chunk, then cancel the active job
            await resp.content.readline()
            jobs = scheduler.get_active_jobs()
            assert jobs
            cancel = await client.delete(f"/inference/{jobs[0].jobId}")
            assert cancel.status == 200
        await asyncio.sleep(0.1)
        assert scheduler.get_active_jobs() == []
    finally:
        await _teardown(registry, scheduler, worker, client, bus)


async def test_images_travel_to_engine_and_reject_loudly(tiny_engine):
    """VERDICT missing #5: images must travel the full protocol (gateway →
    scheduler → worker → engine). No vision family exists yet, so a text
    model must reject with a structured per-model error — not drop the
    pixels silently, not crash the worker — on both generate and chat."""
    bus, registry, scheduler, worker, client = await _stack(tiny_engine)
    try:
        resp = await client.post("/ollama/api/generate", json={
            "model": MODEL, "prompt": "what is in this picture?",
            "stream": False, "images": ["aGVsbG8="]})
        text = json.dumps(await resp.json())
        assert "does not support image inputs" in text, text

        resp = await client.post("/ollama/api/chat", json={
            "model": MODEL, "stream": False, "messages": [
                {"role": "user", "content": "describe",
                 "images": ["aGVsbG8="]}]})
        text = json.dumps(await resp.json())
        assert "does not support image inputs" in text, text

        # worker survives: a plain request still serves
        resp = await client.post("/ollama/api/generate", json={
            "model": MODEL, "prompt": "hello", "stream": False,
            "options": {"num_predict": 4}})
        assert resp.status == 200 and (await resp.json())["done"]
    finally:
        await _teardown(registry, scheduler, worker, client, bus)


async def test_metrics_and_trace_through_real_engine(tiny_engine):
    """ISSUE 1 acceptance: after a request served by the REAL engine worker,
    /metrics carries engine token counters, KV page-pool gauges, and
    kernel-dispatch counters, and /admin/trace/{id} returns a stitched
    gateway+worker timeline including the engine stage spans."""
    bus, registry, scheduler, worker, client = await _stack(tiny_engine)
    try:
        resp = await client.post("/ollama/api/generate", json={
            "model": MODEL, "prompt": "observe me",
            "options": {"temperature": 0, "num_predict": 6},
        })
        assert resp.status == 200
        lines = [json.loads(l) for l in (await resp.text()).strip().splitlines()]
        assert lines[-1]["done"] is True
        await bus.flush()

        text = await (await client.get("/metrics")).text()
        # engine token counters (process-global registry)
        assert f'gridllm_engine_tokens_total{{model="{MODEL}",kind="decode"}}' in text
        assert f'gridllm_engine_tokens_total{{model="{MODEL}",kind="prefill"}}' in text
        # KV page-pool gauges: no pages referenced after the request; the
        # prefix cache (ISSUE 3) may retain released pages as reusable, so
        # free + cached must account for the whole pool
        assert f'gridllm_engine_kv_pages_used{{model="{MODEL}"}} 0' in text
        free = cached = None
        for line in text.splitlines():
            if line.startswith(f'gridllm_engine_kv_pages_free{{model="{MODEL}"}}'):
                free = float(line.rsplit(" ", 1)[1])
            elif line.startswith(f'gridllm_engine_kv_pages_cached{{model="{MODEL}"}}'):
                cached = float(line.rsplit(" ", 1)[1])
        assert free is not None and cached is not None
        assert free + cached == 64
        # kernel-vs-jnp dispatch counters (jnp fallback on the CPU backend)
        # the decode plane's op is attention_ragged with the unified
        # ragged kernel on (ISSUE 6, the default); the legacy ops
        # (attention_verify with speculation, attention_decode without)
        # appear only with GRIDLLM_RAGGED_ATTN=0 — any of the three
        # proves the dispatch counters flow
        assert (
            'gridllm_kernel_dispatch_total{op="attention_ragged",path="jnp"}'
            in text
            or 'gridllm_kernel_dispatch_total{op="attention_verify",path="jnp"}'
            in text
            or 'gridllm_kernel_dispatch_total{op="attention_decode",path="jnp"}'
            in text
        )
        # engine step/occupancy histograms populated
        assert f'gridllm_engine_step_duration_seconds_count{{model="{MODEL}"}}' in text
        assert f'gridllm_engine_batch_occupancy_count{{model="{MODEL}"}}' in text
        # worker-plane job outcomes
        assert 'gridllm_worker_jobs_total{event="completed"}' in text
        # TTFT histogram fed by the streaming path
        assert f'gridllm_request_ttft_seconds_count{{model="{MODEL}"}} 1' in text

        # the stitched trace: gateway + worker sources, engine stage spans
        ids = scheduler.tracer.ids()
        assert ids
        body = await (await client.get(f"/admin/trace/{ids[-1]}")).json()
        names = [s["name"] for s in body["spans"]]
        for expected in ("gateway.request", "queue.wait", "scheduler.dispatch",
                         "gateway.first_token", "worker.execute",
                         "worker.first_token", "engine.prefill",
                         "engine.decode"):
            assert expected in names, (expected, names)
        assert any(s.startswith("worker:") for s in body["sources"])
        decode = next(s for s in body["spans"] if s["name"] == "engine.decode")
        assert decode["meta"]["tokens"] == 6
        # ISSUE 5: the decode span attributes speculative draft outcomes
        assert "specAccepted" in decode["meta"]
        assert "specProposed" in decode["meta"]
        # no leaked active spans on either side
        assert scheduler.tracer.active_count() == 0
        assert worker.tracer.active_count() == 0
    finally:
        await _teardown(registry, scheduler, worker, client, bus)
