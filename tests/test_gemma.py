"""Gemma-2 family: goldens vs HF Gemma2ForCausalLM + engine paths.

Covers the conventions that differ from the llama skeleton (SURVEY.md §4
golden-test strategy): (1+w) RMSNorm, four norms per block, GeGLU,
sqrt(E)-scaled embeddings, query_pre_attn_scalar logits scale, attn/final
logit softcapping, and EVEN-layer sliding-window attention.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gridllm_tpu.models import gemma
from gridllm_tpu.models.configs import get_config
from gridllm_tpu.ops.kvcache import PagedKVCache, PageAllocator

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

CFG = get_config("tiny-gemma2")


@pytest.fixture(scope="module")
def twin():
    hf_cfg = CFG.hf_config()
    torch.manual_seed(0)
    with torch.no_grad():
        model = transformers.Gemma2ForCausalLM(hf_cfg).eval()
    params = gemma.convert_hf_state_dict(CFG, model.state_dict(), jnp.float32)
    return params, model


def test_hf_config_roundtrip():
    hf = CFG.hf_config()
    assert hf.model_type == "gemma2"
    assert hf.sliding_window == CFG.sliding_window
    assert hf.attn_logit_softcapping == CFG.attn_logit_softcap
    assert hf.final_logit_softcapping == CFG.final_logit_softcap
    assert hf.query_pre_attn_scalar == CFG.query_pre_attn_scalar
    assert hf.tie_word_embeddings


def test_forward_matches_hf(twin):
    """Long enough (24 > window=8) that the sliding-window mask on even
    layers actually truncates context — a full-attention bug would show."""
    params, model = twin
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, CFG.vocab_size, size=(2, 24))
    ours = np.asarray(gemma.forward(params, CFG, jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = model(
            input_ids=torch.from_numpy(tokens.astype(np.int64))
        ).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_prefill_decode_match_forward(twin):
    """Paged prefill + N decode steps == cache-free forward on the same
    growing sequence (greedy argmax chain)."""
    params, _ = twin
    prompt = list(range(3, 15))  # 12 tokens > window 8
    cache = PagedKVCache.create(
        CFG.num_layers, num_pages=16, page_size=8,
        num_kv_heads=CFG.num_kv_heads, head_dim=CFG.head_dim_,
        max_slots=2, max_pages_per_slot=8, dtype=jnp.float32,
    )
    alloc = PageAllocator(16, 8, 8)
    alloc.alloc(0, 32)
    row = jnp.asarray(alloc.table_row(0), jnp.int32)

    padded = jnp.asarray(prompt + [0] * (16 - len(prompt)), jnp.int32)
    logits, cache = gemma.prefill(
        params, CFG, padded, jnp.int32(len(prompt)), cache, jnp.int32(0), row)

    seq = list(prompt)
    for _ in range(4):
        ref = np.asarray(gemma.forward(
            params, CFG, jnp.asarray([seq], jnp.int32)))[0, -1]
        np.testing.assert_allclose(
            np.asarray(logits), ref, rtol=2e-4, atol=2e-4)
        nxt = int(np.argmax(ref))
        seq.append(nxt)
        tok = jnp.zeros((2,), jnp.int32).at[0].set(nxt)
        active = jnp.zeros((2,), bool).at[0].set(True)
        dec, cache = gemma.decode_step(params, CFG, tok, cache, active)
        logits = dec[0]


def test_chunked_prefill_matches_whole(twin):
    params, _ = twin
    ids = list(range(2, 26))  # 24 tokens, 3 chunks of 8

    def fresh():
        return PagedKVCache.create(
            CFG.num_layers, num_pages=16, page_size=8,
            num_kv_heads=CFG.num_kv_heads, head_dim=CFG.head_dim_,
            max_slots=2, max_pages_per_slot=8, dtype=jnp.float32,
        )

    alloc = PageAllocator(16, 8, 8)
    alloc.alloc(0, 32)
    row = jnp.asarray(alloc.table_row(0), jnp.int32)

    whole, _ = gemma.prefill(
        params, CFG, jnp.asarray(ids, jnp.int32), jnp.int32(len(ids)),
        fresh(), jnp.int32(0), row)

    cache = fresh()
    for s0 in (0, 8, 16):
        chunked, cache = gemma.prefill_chunk(
            params, CFG, jnp.asarray(ids[s0:s0 + 8], jnp.int32),
            jnp.int32(s0), jnp.int32(8), cache, jnp.int32(0), row)
    np.testing.assert_allclose(
        np.asarray(chunked), np.asarray(whole), rtol=2e-4, atol=2e-4)


def test_engine_serves_gemma2():
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.engine.engine import GenerationRequest

    eng = InferenceEngine(EngineConfig(
        model="tiny-gemma2", max_slots=2, page_size=8, num_pages=32,
        max_pages_per_slot=8, prefill_buckets=(16, 32),
    ))
    res = eng.generate(GenerationRequest(
        id="g1", prompt="hello gemma",
        options={"temperature": 0, "num_predict": 5, "seed": 3},
    ))
    assert res.done_reason in ("stop", "length")
    assert res.eval_count >= 1
    res2 = eng.generate(GenerationRequest(
        id="g2", prompt="hello gemma",
        options={"temperature": 0, "num_predict": 5, "seed": 3},
    ))
    assert res2.token_ids == res.token_ids


def test_sp_mesh_rejected_at_engine_init():
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.parallel.mesh import MeshConfig

    with pytest.raises(ValueError, match="sp"):
        InferenceEngine(EngineConfig(
            model="tiny-gemma2", max_slots=2, page_size=8, num_pages=32,
            max_pages_per_slot=8, prefill_buckets=(16, 32),
            mesh=MeshConfig(sp=2, tp=4),
        ))
