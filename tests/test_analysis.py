"""Analyzer tests (ISSUE 8): every rule must fire on a seeded violation
(a checker that cannot fail is waiving the policy silently), and the
self-run over THIS repo must be clean — that second half is the actual
invariant gate tier-1 runs.

Fixture repos are tiny synthetic trees in tmp_path; rules are exercised
through the same ``run()`` entry the CLI uses.
"""

import json
import subprocess
import sys
from pathlib import Path

from gridllm_tpu.analysis import run
from gridllm_tpu.analysis.rules.dashboard_drift import (
    expand_braces,
    readme_table_metrics,
)

REPO_ROOT = Path(__file__).resolve().parents[1]

# a README configuration table covering every registered env var, so
# fixture repos only trip the violations they seed (generated, not typed)
def _full_env_table() -> str:
    from gridllm_tpu.utils.config import ENV_VARS

    rows = ["## Configuration", "",
            "| Variable | Default | Description |", "|---|---|---|"]
    rows += [f"| `{v.name}` | `{v.default}` | {v.description} |"
             for v in ENV_VARS.values()]
    return "\n".join(rows)


def make_repo(tmp_path: Path, files: dict[str, str]) -> Path:
    defaults = {
        "README.md": _full_env_table() + "\n",
        "gridllm_tpu/__init__.py": "",
        "deploy/grafana-dashboard.json": "{}",
        "deploy/prometheus-alerts.yml": "groups: []",
    }
    for rel, text in {**defaults, **files}.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    return tmp_path


def findings_for(root: Path, rule: str):
    return [f for f in run(root, [rule]) if f.rule == rule]


# -- per-rule seeded violations --------------------------------------------

def test_config_discipline_fires_on_direct_read(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/mod.py": (
        "import os\n"
        "LEVEL = os.environ.get('GRIDLLM_LOG_LEVEL', 'info')\n"
    )})
    msgs = [f.message for f in findings_for(root, "config-discipline")]
    assert any("direct os.environ read of GRIDLLM_LOG_LEVEL" in m
               for m in msgs), msgs


def test_config_discipline_fires_on_unregistered_var(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/mod.py": (
        "from gridllm_tpu.utils.config import env_str\n"
        "X = env_str('GRIDLLM_NO_SUCH_KNOB')\n"
    )})
    msgs = [f.message for f in findings_for(root, "config-discipline")]
    assert any("GRIDLLM_NO_SUCH_KNOB" in m and "ENV_VARS" in m
               for m in msgs), msgs


def test_config_discipline_fires_on_readme_drift(tmp_path):
    # README documents a var the registry does not know
    root = make_repo(tmp_path, {"README.md": _full_env_table() + (
        "\n| `GRIDLLM_GHOST_KNOB` | `1` | not registered anywhere |\n")})
    msgs = [f.message for f in findings_for(root, "config-discipline")]
    assert any("GRIDLLM_GHOST_KNOB" in m and "not registered" in m
               for m in msgs), msgs


def test_config_discipline_fires_on_default_drift(tmp_path):
    # README documents a default that disagrees with the registry
    table = _full_env_table().replace(
        "| `GRIDLLM_MAX_BATCH_SLOTS` | `8` |",
        "| `GRIDLLM_MAX_BATCH_SLOTS` | `16` |")
    assert "| `16` |" in table, "fixture assumes the registry default is 8"
    root = make_repo(tmp_path, {"README.md": table + "\n"})
    msgs = [f.message for f in findings_for(root, "config-discipline")]
    assert any("GRIDLLM_MAX_BATCH_SLOTS" in m and "default" in m
               for m in msgs), msgs


def test_lock_discipline_fires_on_unguarded_mutation(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/engine_like.py": (
        "class E:\n"
        "    def bad(self, slot):\n"
        "        self.alloc.free(slot)\n"
        "    def good(self, slot):\n"
        "        with self._alloc_lock:\n"
        "            self.alloc.free(slot)\n"
    )})
    fs = findings_for(root, "lock-discipline")
    assert len(fs) == 1 and fs[0].line == 3, fs


def test_lock_discipline_fires_on_order_inversion(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/engine_like.py": (
        "class E:\n"
        "    def inverted(self):\n"
        "        with self.dispatch_lock:\n"
        "            with self._alloc_lock:\n"
        "                pass\n"
        "    def single_stmt_inverted(self):\n"
        "        with self.dispatch_lock, self._alloc_lock:\n"
        "            pass\n"
        "    def correct(self):\n"
        "        with self._alloc_lock, self.dispatch_lock:\n"
        "            pass\n"
        "    def also_correct(self):\n"
        "        with self._alloc_lock:\n"
        "            with self.dispatch_lock:\n"
        "                pass\n"
    )})
    fs = findings_for(root, "lock-discipline")
    assert sorted(f.line for f in fs) == [4, 7], fs


def test_dashboard_drift_fires_on_phantom_panel_metric(tmp_path):
    root = make_repo(tmp_path, {
        "gridllm_tpu/m.py": (
            "from gridllm_tpu.obs import default_registry\n"
            "C = default_registry().counter(\n"
            "    'gridllm_real_total', 'Real.', ('model',))\n"
        ),
        "deploy/grafana-dashboard.json":
            '{"expr": "rate(gridllm_phantom_total[5m])"}',
        "README.md": _full_env_table() +
            "\n| `gridllm_real_total` (model) | real |\n",
    })
    msgs = [f.message for f in findings_for(root, "dashboard-drift")]
    assert any("gridllm_phantom_total" in m and "no code registers" in m
               for m in msgs), msgs


def test_dashboard_drift_fires_on_undocumented_metric(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/m.py": (
        "from gridllm_tpu.obs import default_registry\n"
        "C = default_registry().counter(\n"
        "    'gridllm_undocumented_total', 'Help.', ('model',))\n"
    )})
    msgs = [f.message for f in findings_for(root, "dashboard-drift")]
    assert any("gridllm_undocumented_total" in m
               and "README metrics table" in m for m in msgs), msgs


def test_dashboard_drift_fires_on_wrong_suffix(tmp_path):
    # a counter referenced with a histogram-only series suffix
    root = make_repo(tmp_path, {
        "gridllm_tpu/m.py": (
            "from gridllm_tpu.obs import default_registry\n"
            "C = default_registry().counter(\n"
            "    'gridllm_real_total', 'Real.', ('model',))\n"
        ),
        "deploy/prometheus-alerts.yml":
            "expr: gridllm_real_total_bucket > 0",
        "README.md": _full_env_table() +
            "\n| `gridllm_real_total` (model) | real |\n",
    })
    msgs = [f.message for f in findings_for(root, "dashboard-drift")]
    assert any("gridllm_real_total_bucket" in m for m in msgs), msgs


def test_dashboard_drift_fires_on_bare_histogram_family_in_query(tmp_path):
    # a Grafana QUERY naming the family references a series that never
    # exists (only _bucket/_sum/_count are exported) — flat-panel drift.
    # The same family name in prose (title) stays legal.
    root = make_repo(tmp_path, {
        "gridllm_tpu/m.py": (
            "from gridllm_tpu.obs import default_registry\n"
            "H = default_registry().histogram(\n"
            "    'gridllm_lat_seconds', 'Latency.')\n"
        ),
        "deploy/grafana-dashboard.json": (
            '{"title": "gridllm_lat_seconds p95",\n'
            ' "expr": "histogram_quantile(0.95, rate(gridllm_lat_seconds[5m]))"}'
        ),
        "README.md": _full_env_table() +
            "\n| `gridllm_lat_seconds` | latency |\n",
    })
    fs = [f for f in findings_for(root, "dashboard-drift")
          if "histogram family" in f.message]
    assert len(fs) == 1 and fs[0].line == 2, fs


def test_jit_discipline_fires_on_unwrapped_and_dirty_bodies(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/engine/engine.py": (
        "import jax\n"
        "from functools import partial\n"
        "class InferenceEngine:\n"
        "    def _build_fns(self):\n"
        "        @partial(jax.jit, static_argnames=('k',))\n"
        "        def unwrapped_fn(params, toks, k):\n"
        "            if k:\n"                      # static: fine
        "                n = toks.sum().item()\n"  # .item() inside jit
        "            if toks > 0:\n"               # traced branch
        "                pass\n"
        "            if params is None:\n"         # structure check: fine
        "                pass\n"
        "            return toks\n"
        "        self._fn = jax.jit(lambda p: p)\n"  # inline, unwrapped
        "        @partial(jax.jit)\n"
        "        def wrapped_fn(x):\n"
        "            return x\n"
        "        self._ok = self.perf.wrap('ok', wrapped_fn)\n"
    )})
    msgs = [f.message for f in findings_for(root, "jit-discipline")]
    assert any("unwrapped_fn" in m and "perf.wrap" in m for m in msgs), msgs
    assert any(".item()" in m for m in msgs), msgs
    assert any("traced value" in m and "toks" in m for m in msgs), msgs
    assert any("inline jax.jit" in m for m in msgs), msgs
    assert not any(m.startswith("jitted function wrapped_fn(")
                   for m in msgs), msgs
    assert not any("params" in m and "traced" in m for m in msgs), msgs


def test_span_pairing_fires_on_leaky_span(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/svc.py": (
        "class S:\n"
        "    def leaky(self, rid):\n"
        "        span = self.tracer.begin(rid, 'x')\n"
        "        self.work()\n"
        "        self.tracer.end(span)\n"        # not in a finally
        "    def dropped(self, rid):\n"
        "        self.tracer.begin(rid, 'x')\n"  # discarded outright
        "    def safe(self, rid):\n"
        "        span = self.tracer.begin(rid, 'x')\n"
        "        try:\n"
        "            self.work()\n"
        "        finally:\n"
        "            self.tracer.end(span)\n"
        "    def handoff(self, rid):\n"
        "        self._spans[rid] = self.tracer.begin(rid, 'x')\n"
    )})
    fs = findings_for(root, "span-pairing")
    assert sorted(f.line for f in fs) == [3, 7], fs


def test_span_pairing_fires_when_try_does_not_cover_begin(tmp_path):
    # an end()-in-finally elsewhere in the function must not count when a
    # statement between begin() and the try can raise with the span open
    root = make_repo(tmp_path, {"gridllm_tpu/svc.py": (
        "class S:\n"
        "    def gap(self, rid):\n"
        "        span = self.tracer.begin(rid, 'x')\n"
        "        self.prep()\n"              # raises -> span leaks
        "        try:\n"
        "            self.work()\n"
        "        finally:\n"
        "            self.tracer.end(span)\n"
        "    def begin_inside_try(self, rid):\n"
        "        try:\n"
        "            span = self.tracer.begin(rid, 'x')\n"
        "            self.work()\n"
        "        finally:\n"
        "            self.tracer.end(span)\n"
    )})
    fs = findings_for(root, "span-pairing")
    assert sorted(f.line for f in fs) == [3], fs


def test_config_discipline_other_tables_do_not_satisfy_doc_check(tmp_path):
    # drop one var's Configuration-table row but mention it in another
    # markdown table: the doc check must still fire
    table = _full_env_table()
    lines = [l for l in table.splitlines() if "GRIDLLM_PALLAS" not in l]
    readme = "\n".join(lines) + (
        "\n\n## Metrics\n"
        "| `gridllm_kernel_dispatch_total` | per GRIDLLM_PALLAS policy |\n")
    root = make_repo(tmp_path, {"README.md": readme})
    msgs = [f.message for f in findings_for(root, "config-discipline")]
    assert any("GRIDLLM_PALLAS" in m and "missing from the README" in m
               for m in msgs), msgs


def test_metric_hygiene_audits_keyword_labelnames(tmp_path):
    root = make_repo(tmp_path, {
        "gridllm_tpu/m.py": (
            "from gridllm_tpu.obs import default_registry\n"
            "A = default_registry().counter(\n"
            "    'gridllm_kw_total', 'Kw.', labelnames=('request_id',))\n"
            "B = default_registry().counter(\n"
            "    'gridllm_splat_total', 'Splat.', **extra)\n"
        ),
        "README.md": _full_env_table() +
            "\n| `gridllm_kw_total` `gridllm_splat_total` | seeded |\n",
    })
    msgs = [f.message for f in findings_for(root, "metric-hygiene")]
    assert any("gridllm_kw_total" in m and "request_id" in m
               for m in msgs), msgs
    assert any("gridllm_splat_total" in m and "audited" in m
               for m in msgs), msgs


def test_metric_hygiene_fires_on_bad_name_label_help(tmp_path):
    root = make_repo(tmp_path, {
        "gridllm_tpu/m.py": (
            "from gridllm_tpu.obs import default_registry\n"
            "A = default_registry().counter(\n"
            "    'BadName_total', 'Bad name.')\n"
            "B = default_registry().counter(\n"
            "    'gridllm_leaky_total', 'Bad label.', ('job_id',))\n"
            "C = default_registry().counter(\n"
            "    'gridllm_helpless_total', '')\n"
        ),
        "README.md": _full_env_table() +
            "\n| `BadName_total` `gridllm_leaky_total` "
            "`gridllm_helpless_total` | seeded |\n",
    })
    msgs = [f.message for f in findings_for(root, "metric-hygiene")]
    assert any("BadName_total" in m and "naming" in m for m in msgs), msgs
    assert any("job_id" in m for m in msgs), msgs
    assert any("gridllm_helpless_total" in m and "help" in m
               for m in msgs), msgs


def test_metric_hygiene_confines_tenant_labels_to_usage_ledger(tmp_path):
    # ISSUE 16: a `tenant` label is legal only in obs/usage.py (where the
    # TenantLRU bounds its cardinality); the identical registration in any
    # other module must fire
    root = make_repo(tmp_path, {
        "gridllm_tpu/rogue.py": (
            "from gridllm_tpu.obs import default_registry\n"
            "A = default_registry().counter(\n"
            "    'gridllm_rogue_total', 'Rogue.', ('tenant', 'model'))\n"
        ),
        "gridllm_tpu/obs/usage.py": (
            "from gridllm_tpu.obs import default_registry\n"
            "B = default_registry().counter(\n"
            "    'gridllm_ledger_total', 'Ledger.', ('tenant', 'model'))\n"
        ),
        "README.md": _full_env_table() +
            "\n| `gridllm_rogue_total` `gridllm_ledger_total` | seeded |\n",
    })
    msgs = [f.message for f in findings_for(root, "metric-hygiene")]
    assert any("gridllm_rogue_total" in m and "tenant" in m
               for m in msgs), msgs
    assert not any("gridllm_ledger_total" in m for m in msgs), msgs


# -- channel-discipline (ISSUE 13) ------------------------------------------

# a minimal bus/base.py channel registry for fixture repos: two families
# (one fixed, one parameterized), registry-derived durable_channel
_FIXTURE_BUS = """\
CHANNELS = {}


def register_channel(family, **kw):
    CHANNELS[family] = kw


CH_PING = "svc:ping"


def box_channel(box_id):
    return f"svc:box:{box_id}"


def durable_channel(channel):
    return channel in CHANNELS


register_channel(
    "svc:ping", pattern="svc:ping", payload="keys", keys=("a", "b"),
    durable=False, publishers=("gridllm_tpu/pub.py",),
    subscribers=("gridllm_tpu/sub.py",), helper="CH_PING",
    description="fixture fixed channel")
register_channel(
    "svc:box", pattern="svc:box:{box_id}", payload="keys", keys=("x",),
    durable=True, publishers=("gridllm_tpu/pub.py",),
    subscribers=("gridllm_tpu/sub.py",), helper="box_channel",
    description="fixture parameterized channel")
"""

_FIXTURE_CHANNEL_TABLE = (
    "\n## Bus channels\n\n"
    "| Channel | Durable | Payload | Who |\n|---|---|---|---|\n"
    "| `svc:ping` | no | `keys` | pub → sub |\n"
    "| `svc:box:{box_id}` | yes | `keys` | pub → sub |\n")


def _channel_repo(tmp_path, **overrides):
    files = {
        "gridllm_tpu/bus/base.py": _FIXTURE_BUS,
        "gridllm_tpu/pub.py": (
            "import json\n"
            "from gridllm_tpu.bus.base import CH_PING, box_channel\n"
            "async def go(bus):\n"
            "    await bus.publish(CH_PING, json.dumps({'a': 1, 'b': 2}))\n"
            "    await bus.publish(box_channel('1'), json.dumps({'x': 1}))\n"
        ),
        "gridllm_tpu/sub.py": (
            "from gridllm_tpu.bus.base import CH_PING, box_channel\n"
            "async def listen(bus, h):\n"
            "    await bus.subscribe(CH_PING, h)\n"
            "    await bus.subscribe(box_channel('1'), h)\n"
        ),
        "README.md": _full_env_table() + _FIXTURE_CHANNEL_TABLE,
    }
    files.update(overrides)
    return make_repo(tmp_path, files)


def test_channel_discipline_clean_fixture(tmp_path):
    root = _channel_repo(tmp_path)
    assert findings_for(root, "channel-discipline") == []


def test_channel_discipline_fires_on_raw_literal_and_fstring(tmp_path):
    root = _channel_repo(tmp_path, **{"gridllm_tpu/pub.py": (
        "import json\n"
        "from gridllm_tpu.bus.base import CH_PING, box_channel\n"
        "async def go(bus, rid):\n"
        "    await bus.publish(CH_PING, json.dumps({'a': 1, 'b': 2}))\n"
        "    await bus.publish(box_channel('1'), json.dumps({'x': 1}))\n"
        "    await bus.publish('svc:ping', '{}')\n"
        "    await bus.subscribe(f'svc:box:{rid}', go)\n"
    )})
    msgs = [f.message for f in findings_for(root, "channel-discipline")]
    assert any("raw channel literal 'svc:ping'" in m for m in msgs), msgs
    assert any("f-string channel name" in m for m in msgs), msgs


def test_channel_discipline_fires_on_payload_key_drift(tmp_path):
    # publishes an undeclared key 'c' and never sends declared key 'b'
    root = _channel_repo(tmp_path, **{"gridllm_tpu/pub.py": (
        "import json\n"
        "from gridllm_tpu.bus.base import CH_PING, box_channel\n"
        "async def go(bus):\n"
        "    await bus.publish(CH_PING, json.dumps({'a': 1, 'c': 2}))\n"
        "    await bus.publish(box_channel('1'), json.dumps({'x': 1}))\n"
    )})
    msgs = [f.message for f in findings_for(root, "channel-discipline")]
    assert any("payload key 'c'" in m and "not declared" in m
               for m in msgs), msgs
    assert any("declares payload key 'b'" in m
               and "no publisher ever sends" in m for m in msgs), msgs


def test_channel_discipline_fires_on_undeclared_direction(tmp_path):
    # sub.py publishes on a family it is only declared to subscribe to
    root = _channel_repo(tmp_path, **{"gridllm_tpu/sub.py": (
        "import json\n"
        "from gridllm_tpu.bus.base import CH_PING, box_channel\n"
        "async def listen(bus, h):\n"
        "    await bus.subscribe(CH_PING, h)\n"
        "    await bus.subscribe(box_channel('1'), h)\n"
        "    await bus.publish(CH_PING, json.dumps({'a': 1, 'b': 2}))\n"
    )})
    msgs = [f.message for f in findings_for(root, "channel-discipline")]
    assert any("not a declared publisher" in m for m in msgs), msgs


def test_channel_discipline_fires_on_hardcoded_durability(tmp_path):
    bus = _FIXTURE_BUS.replace(
        "def durable_channel(channel):\n    return channel in CHANNELS",
        "def durable_channel(channel):\n"
        "    return channel in ('svc:box',)")
    root = _channel_repo(tmp_path, **{"gridllm_tpu/bus/base.py": bus})
    msgs = [f.message for f in findings_for(root, "channel-discipline")]
    assert any("hardcodes channel name" in m and "derive" in m
               for m in msgs), msgs


def test_channel_discipline_fires_on_readme_table_drift(tmp_path):
    table = _FIXTURE_CHANNEL_TABLE.replace(
        "| `svc:box:{box_id}` | yes |", "| `svc:box:{box_id}` | no |")
    root = _channel_repo(
        tmp_path, **{"README.md": _full_env_table() + table})
    msgs = [f.message for f in findings_for(root, "channel-discipline")]
    assert any("durability" in m and "'no'" in m and "'yes'" in m
               for m in msgs), msgs
    # and a missing row is drift too
    root2 = _channel_repo(tmp_path / "r2", **{
        "README.md": _full_env_table() + _FIXTURE_CHANNEL_TABLE.replace(
            "| `svc:ping` | no | `keys` | pub → sub |\n", "")})
    msgs2 = [f.message for f in findings_for(root2, "channel-discipline")]
    assert any("'svc:ping'" in m and "missing from the README" in m
               for m in msgs2), msgs2
    # and so is the Publishers → subscribers column
    root3 = _channel_repo(tmp_path / "r3", **{
        "README.md": _full_env_table() + _FIXTURE_CHANNEL_TABLE.replace(
            "| `svc:ping` | no | `keys` | pub → sub |",
            "| `svc:ping` | no | `keys` | sub → pub |")})
    msgs3 = [f.message for f in findings_for(root3, "channel-discipline")]
    assert any("direction" in m and "sub → pub" in m for m in msgs3), msgs3


def test_channel_discipline_fires_on_helper_pattern_drift(tmp_path):
    bus = _FIXTURE_BUS.replace(
        'def box_channel(box_id):\n    return f"svc:box:{box_id}"',
        'def box_channel(box_id):\n    return f"svc:crate:{box_id}"')
    root = _channel_repo(tmp_path, **{"gridllm_tpu/bus/base.py": bus})
    msgs = [f.message for f in findings_for(root, "channel-discipline")]
    assert any("box_channel()" in m and "svc:crate" in m
               for m in msgs), msgs


# -- event-discipline (ISSUE 17) --------------------------------------------

# a minimal obs/timeline.py EVENTS registry for fixture repos
_FIXTURE_EVENTS = """\
EVENTS = {}


def register_event(name, **kw):
    EVENTS[name] = kw


register_event("svc.started", keys=("worker",),
               modules=("gridllm_tpu/svc.py",))
register_event("svc.stopped", keys=("reason", "worker"),
               modules=("gridllm_tpu/svc.py",))
"""

_FIXTURE_SVC = """\
class Svc:
    def __init__(self, flightrec, worker_id):
        self.flightrec = flightrec
        self.worker_id = worker_id

    def start(self):
        self.flightrec.record("svc", "started", worker=self.worker_id)

    def stop(self, reason):
        self.flightrec.record("svc", "stopped", worker=self.worker_id,
                              reason=reason)
"""

_FIXTURE_EVENT_TABLE = (
    "\n## Timeline events\n\n"
    "| Event | Payload keys | Emitted from |\n|---|---|---|\n"
    "| `svc.started` | `worker` | svc |\n"
    "| `svc.stopped` | `reason, worker` | svc |\n")


def _event_repo(tmp_path, **overrides):
    files = {
        "gridllm_tpu/obs/timeline.py": _FIXTURE_EVENTS,
        "gridllm_tpu/svc.py": _FIXTURE_SVC,
        "README.md": _full_env_table() + _FIXTURE_EVENT_TABLE,
    }
    files.update(overrides)
    return make_repo(tmp_path, files)


def test_event_discipline_clean_fixture(tmp_path):
    root = _event_repo(tmp_path)
    assert findings_for(root, "event-discipline") == []


def test_event_discipline_fires_on_undeclared_event_and_key(tmp_path):
    root = _event_repo(tmp_path, **{"gridllm_tpu/svc.py": _FIXTURE_SVC + (
        "\n"
        "    def crash(self):\n"
        "        self.flightrec.record('svc', 'crashed', worker='w')\n"
        "        self.flightrec.record('svc', 'started', worker='w',\n"
        "                              extra=1)\n"
    )})
    msgs = [f.message for f in findings_for(root, "event-discipline")]
    assert any("'svc.crashed'" in m and "not declared" in m
               for m in msgs), msgs
    assert any("payload key 'extra'" in m for m in msgs), msgs


def test_event_discipline_fires_on_unresolvable_and_splat(tmp_path):
    root = _event_repo(tmp_path, **{"gridllm_tpu/svc.py": _FIXTURE_SVC + (
        "\n"
        "    def weird(self, ev, fields):\n"
        "        self.flightrec.record('svc', ev)\n"
        "        self.flightrec.record('svc', 'started', **fields)\n"
    )})
    msgs = [f.message for f in findings_for(root, "event-discipline")]
    assert any("statically unresolvable" in m for m in msgs), msgs
    assert any("dynamic **fields" in m and "open_keys" in m
               for m in msgs), msgs


def test_event_discipline_fires_on_dead_declaration(tmp_path):
    events = _FIXTURE_EVENTS + (
        'register_event("svc.ghost", keys=("worker",),\n'
        '               modules=("gridllm_tpu/svc.py",))\n')
    table = _FIXTURE_EVENT_TABLE.replace(
        "| `svc.stopped`",
        "| `svc.ghost` | `worker` | svc |\n| `svc.stopped`")
    root = _event_repo(tmp_path, **{
        "gridllm_tpu/obs/timeline.py": events,
        "README.md": _full_env_table() + table})
    msgs = [f.message for f in findings_for(root, "event-discipline")]
    assert any("'svc.ghost'" in m and "no module ever emits" in m
               for m in msgs), msgs


def test_event_discipline_fires_on_readme_table_drift(tmp_path):
    table = _FIXTURE_EVENT_TABLE.replace(
        "| `svc.started` | `worker` |", "| `svc.started` | `job` |")
    root = _event_repo(
        tmp_path, **{"README.md": _full_env_table() + table})
    msgs = [f.message for f in findings_for(root, "event-discipline")]
    assert any("'svc.started'" in m and "keys" in m for m in msgs), msgs
    # a missing row is drift too
    root2 = _event_repo(tmp_path / "r2", **{
        "README.md": _full_env_table() + _FIXTURE_EVENT_TABLE.replace(
            "| `svc.started` | `worker` | svc |\n", "")})
    msgs2 = [f.message for f in findings_for(root2, "event-discipline")]
    assert any("'svc.started'" in m and "missing from the README" in m
               for m in msgs2), msgs2


def test_event_discipline_resolves_emit_event_envelope(tmp_path):
    # emit_event envelope attrs (member/request_id/stamp) are not payload
    # keys; a payload kwarg outside the registry still fires
    events = _FIXTURE_EVENTS + (
        'register_event("svc.edge", keys=("channel",),\n'
        '               modules=("gridllm_tpu/edge.py",))\n')
    table = _FIXTURE_EVENT_TABLE + "| `svc.edge` | `channel` | edge |\n"
    root = _event_repo(tmp_path, **{
        "gridllm_tpu/obs/timeline.py": events,
        "gridllm_tpu/edge.py": (
            "from gridllm_tpu.obs.timeline import emit_event\n"
            "def send(rid, stamp):\n"
            "    emit_event('svc.edge', member='m', request_id=rid,\n"
            "               stamp=stamp, channel='c')\n"),
        "README.md": _full_env_table() + table})
    assert findings_for(root, "event-discipline") == []
    root2 = _event_repo(tmp_path / "r2", **{
        "gridllm_tpu/obs/timeline.py": events,
        "gridllm_tpu/edge.py": (
            "from gridllm_tpu.obs.timeline import emit_event\n"
            "def send(rid):\n"
            "    emit_event('svc.edge', request_id=rid, channel='c',\n"
            "               shard=3)\n"),
        "README.md": _full_env_table() + table})
    msgs = [f.message for f in findings_for(root2, "event-discipline")]
    assert any("payload key 'shard'" in m for m in msgs), msgs


# -- async-discipline (ISSUE 13) --------------------------------------------

def test_async_discipline_fires_on_blocking_calls(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/gateway/svc.py": (
        "import time, subprocess, asyncio\n"
        "async def bad(my_lock, path):\n"
        "    time.sleep(1)\n"                        # 3
        "    subprocess.run(['x'])\n"                # 4
        "    open('f').read()\n"                     # 5
        "    path.read_text()\n"                     # 6
        "    my_lock.acquire()\n"                    # 7
        "    my_lock.acquire(True)\n"                # 8: still unbounded
        "    time.sleep(0)  # async-ok\n"            # waived
        "    my_lock.acquire(timeout=1)\n"           # bounded: fine
        "    my_lock.acquire(False)\n"               # non-blocking: fine
        "    my_lock.acquire(blocking=False)\n"      # non-blocking: fine
        "    await asyncio.to_thread(time.sleep, 1)\n"  # routed: fine
        "def sync_helper():\n"
        "    time.sleep(1)\n"                        # sync def: fine
        "async def uses_closure():\n"
        "    def thread_target():\n"
        "        time.sleep(1)\n"                    # nested sync: fine
        "    return thread_target\n"
    )})
    fs = findings_for(root, "async-discipline")
    assert sorted(f.line for f in fs) == [3, 4, 5, 6, 7, 8], fs
    msgs = [f.message for f in fs]
    assert any("asyncio.sleep" in m for m in msgs), msgs
    assert any("lock.acquire" in m for m in msgs), msgs


def test_async_discipline_ignores_other_subsystems(tmp_path):
    # models/ops code is sync-world; the rule scopes to the async planes
    root = make_repo(tmp_path, {"gridllm_tpu/ops/helper.py": (
        "import time\n"
        "async def odd_but_out_of_scope():\n"
        "    time.sleep(1)\n"
    )})
    assert findings_for(root, "async-discipline") == []


# -- fault-coverage (ISSUE 13) ----------------------------------------------

_FIXTURE_FAULTS = (
    'SITES = (\n    "svc.alive",\n    "svc.dead",\n)\n'
    "def check(site):\n    return False\n"
    "def inject(site):\n    check(site)\n"
)

_FIXTURE_FAULT_TABLE = (
    "\n## Faults\n\n| site | effect |\n|---|---|\n"
    "| `svc.alive` | fixture |\n| `svc.dead` | fixture |\n")


def test_fault_coverage_fires_on_dead_and_unregistered_sites(tmp_path):
    root = make_repo(tmp_path, {
        "gridllm_tpu/faults.py": _FIXTURE_FAULTS,
        "gridllm_tpu/bus/mod.py": (
            "from gridllm_tpu import faults\n"
            "def f():\n"
            "    faults.check('svc.alive')\n"
            "    faults.inject('svc.ghost')\n"
        ),
        "README.md": _full_env_table() + _FIXTURE_FAULT_TABLE,
    })
    msgs = [f.message for f in findings_for(root, "fault-coverage")]
    assert any("'svc.dead'" in m and "no live inject()/check()" in m
               for m in msgs), msgs
    assert any("'svc.ghost'" in m and "not registered" in m
               for m in msgs), msgs


def test_fault_coverage_fires_on_nonliteral_site_and_readme_drift(tmp_path):
    root = make_repo(tmp_path, {
        "gridllm_tpu/faults.py": _FIXTURE_FAULTS,
        "gridllm_tpu/bus/mod.py": (
            "from gridllm_tpu import faults\n"
            "def f(site):\n"
            "    faults.check(site)\n"
            "    faults.check('svc.alive')\n"
            "    faults.check('svc.dead')\n"
        ),
        # table documents a ghost site and misses svc.dead
        "README.md": _full_env_table() +
            "\n## Faults\n\n| site | effect |\n|---|---|\n"
            "| `svc.alive` | fixture |\n| `svc.ghost` | fixture |\n",
    })
    msgs = [f.message for f in findings_for(root, "fault-coverage")]
    assert any("literal site name" in m for m in msgs), msgs
    assert any("'svc.ghost'" in m and "not registered" in m
               for m in msgs), msgs
    assert any("'svc.dead'" in m and "missing from the README" in m
               for m in msgs), msgs


def test_fault_coverage_fires_on_uncovered_critical_subsystem(tmp_path):
    # a bus/ directory exists but carries no live site
    root = make_repo(tmp_path, {
        "gridllm_tpu/faults.py": _FIXTURE_FAULTS,
        "gridllm_tpu/bus/mod.py": "def quiet():\n    pass\n",
        "gridllm_tpu/other.py": (
            "from gridllm_tpu import faults\n"
            "def f():\n"
            "    faults.check('svc.alive')\n"
            "    faults.check('svc.dead')\n"
        ),
        "README.md": _full_env_table() + _FIXTURE_FAULT_TABLE,
    })
    msgs = [f.message for f in findings_for(root, "fault-coverage")]
    assert any("critical subsystem 'bus'" in m for m in msgs), msgs


def test_new_rules_cli_rule_filtering(tmp_path):
    """--rule runs exactly the selected new rules (ISSUE 13 satellite):
    one seeded violation each, reported under the right rule name."""
    root = make_repo(tmp_path, {
        "gridllm_tpu/faults.py": _FIXTURE_FAULTS,
        "gridllm_tpu/gateway/svc.py": (
            "import time\n"
            "async def bad(bus):\n"
            "    time.sleep(1)\n"
            "    await bus.publish('raw:chan', '{}')\n"
        ),
        "gridllm_tpu/bus/mod.py": (
            "from gridllm_tpu import faults\n"
            "def f():\n    faults.check('svc.alive')\n"
        ),
        "README.md": _full_env_table() + _FIXTURE_FAULT_TABLE,
    })
    proc = subprocess.run(
        [sys.executable, "-m", "gridllm_tpu.analysis", "--json",
         "--rule", "channel-discipline", "--rule", "async-discipline",
         "--rule", "fault-coverage", "--root", str(root)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    fired = {f["rule"] for f in payload["findings"]}
    assert fired == {"channel-discipline", "async-discipline",
                     "fault-coverage"}, payload["findings"]


# -- kernel-parity (gridcheck v3) -------------------------------------------

# a self-consistent fixture kernel surface: registry + kernel module +
# reference + test + README table; individual tests then break one leg
_FIXTURE_KERNEL_REGISTRY = (
    "KERNELS = (\n"
    "    KernelSpec(\n"
    "        name='my_kernel', reference='attention:my_ref',\n"
    "        dispatch='my_op', rtol=1e-2, atol=1e-2,\n"
    "        test='tests/test_my.py::test_my_kernel_matches_ref',\n"
    "        description='fixture'),\n"
    ")\n"
    "EXTRA_DISPATCH_LABELS = {}\n"
)
_FIXTURE_KERNEL_FILES = {
    "gridllm_tpu/ops/kernels.py": _FIXTURE_KERNEL_REGISTRY,
    "gridllm_tpu/ops/pallas_kernels.py": (
        "from jax.experimental import pallas as pl\n"
        "def my_kernel(x):\n"
        "    return pl.pallas_call(None)(x)\n"
    ),
    "gridllm_tpu/ops/attention.py": (
        "from gridllm_tpu.ops.kvcache import record_kernel_path\n"
        "def my_ref(x):\n"
        "    return x\n"
        "def dispatch(x):\n"
        "    record_kernel_path('my_op', True)\n"
        "    return x\n"
    ),
    "tests/test_my.py": (
        "def test_my_kernel_matches_ref():\n"
        "    pass\n"
    ),
}
_FIXTURE_KERNEL_README = (
    "\n## Kernels\n\n"
    "| Kernel | Reference | Dispatch | Tolerance | Test |\n"
    "|---|---|---|---|---|\n"
    "| `my_kernel` | `my_ref` | `my_op` | `1e-2 / 1e-2` | "
    "`tests/test_my.py::test_my_kernel_matches_ref` |\n"
)


def _kernel_repo(tmp_path, **overrides):
    files = {**_FIXTURE_KERNEL_FILES,
             "README.md": _full_env_table() + _FIXTURE_KERNEL_README}
    files.update(overrides)
    return make_repo(tmp_path, files)


def test_kernel_parity_clean_fixture(tmp_path):
    root = _kernel_repo(tmp_path)
    assert findings_for(root, "kernel-parity") == []


def test_kernel_parity_fires_on_unregistered_pallas_call(tmp_path):
    # fallback direction (no fixture registry): the imported KERNELS is
    # the source of truth and the stray pallas_call is flagged
    root = make_repo(tmp_path, {"gridllm_tpu/ops/rogue.py": (
        "from jax.experimental import pallas as pl\n"
        "def rogue_kernel(x):\n"
        "    return pl.pallas_call(None)(x)\n"
    )})
    msgs = [f.message for f in findings_for(root, "kernel-parity")]
    assert any("rogue_kernel" in m and "not a registered kernel" in m
               for m in msgs), msgs


def test_kernel_parity_fires_on_unregistered_call_with_registry(tmp_path):
    root = _kernel_repo(tmp_path, **{
        "gridllm_tpu/ops/pallas_kernels.py":
            _FIXTURE_KERNEL_FILES["gridllm_tpu/ops/pallas_kernels.py"] + (
                "def stray(x):\n"
                "    return pl.pallas_call(None)(x)\n"),
    })
    msgs = [f.message for f in findings_for(root, "kernel-parity")]
    assert any("stray" in m and "not a registered kernel" in m
               for m in msgs), msgs


def test_kernel_parity_fires_on_stale_registry_row(tmp_path):
    # registered kernel whose entry fn lost its pallas_call (and one
    # that does not exist at all)
    root = _kernel_repo(tmp_path, **{
        "gridllm_tpu/ops/pallas_kernels.py": (
            "def my_kernel(x):\n"
            "    return x\n"),
    })
    msgs = [f.message for f in findings_for(root, "kernel-parity")]
    assert any("no pl.pallas_call" in m for m in msgs), msgs


def test_kernel_parity_fires_on_missing_reference_and_test(tmp_path):
    root = _kernel_repo(tmp_path, **{
        "gridllm_tpu/ops/attention.py": (
            "from gridllm_tpu.ops.kvcache import record_kernel_path\n"
            "def dispatch(x):\n"
            "    record_kernel_path('my_op', True)\n"
            "    return x\n"),
        "tests/test_my.py": "def test_something_else():\n    pass\n",
    })
    msgs = [f.message for f in findings_for(root, "kernel-parity")]
    assert any("does not resolve to a function" in m for m in msgs), msgs
    assert any("not found in tests/test_my.py" in m for m in msgs), msgs


def test_kernel_parity_fires_on_dispatch_label_drift_both_ways(tmp_path):
    # recorded label the registry doesn't know + declared label nobody
    # records
    root = _kernel_repo(tmp_path, **{
        "gridllm_tpu/ops/attention.py": (
            "from gridllm_tpu.ops.kvcache import record_kernel_path\n"
            "def my_ref(x):\n"
            "    return x\n"
            "def dispatch(x):\n"
            "    record_kernel_path('mystery_op', True)\n"
            "    return x\n"),
    })
    msgs = [f.message for f in findings_for(root, "kernel-parity")]
    assert any("'mystery_op' is not declared" in m for m in msgs), msgs
    assert any("'my_op' is never recorded" in m for m in msgs), msgs


def test_kernel_parity_fires_on_readme_drift_both_ways(tmp_path):
    phantom = (
        "\n## Kernels\n\n"
        "| Kernel | Reference | Dispatch | Tolerance | Test |\n"
        "|---|---|---|---|---|\n"
        "| `ghost_kernel` | `x` | `y` | `1 / 1` | `t` |\n"
    )
    root = _kernel_repo(tmp_path,
                        **{"README.md": _full_env_table() + phantom})
    msgs = [f.message for f in findings_for(root, "kernel-parity")]
    assert any("ghost_kernel" in m and "not registered" in m
               for m in msgs), msgs
    assert any("'my_kernel' missing from the README" in m
               for m in msgs), msgs


def test_kernel_parity_fires_on_readme_cell_drift(tmp_path):
    wrong_tol = _FIXTURE_KERNEL_README.replace("`1e-2 / 1e-2`",
                                               "`5e-1 / 5e-1`")
    root = _kernel_repo(tmp_path,
                        **{"README.md": _full_env_table() + wrong_tol})
    msgs = [f.message for f in findings_for(root, "kernel-parity")]
    assert any("tolerance cell" in m for m in msgs), msgs
    # the Differential-test column is part of the contract too
    wrong_test = _FIXTURE_KERNEL_README.replace(
        "`tests/test_my.py::test_my_kernel_matches_ref`",
        "`tests/test_my.py::test_totally_wrong_name`")
    root2 = _kernel_repo(tmp_path / "t2",
                         **{"README.md": _full_env_table() + wrong_test})
    msgs2 = [f.message for f in findings_for(root2, "kernel-parity")]
    assert any("column 5" in m and "test_totally_wrong_name" in m
               for m in msgs2), msgs2


# -- dtype-discipline (gridcheck v3) ----------------------------------------

def test_dtype_discipline_fires_on_dtype_less_construction(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/ops/mod.py": (
        "import jax.numpy as jnp\n"
        "X = jnp.asarray([1, 2])\n"
        "Y = jnp.array([1.0])\n"
        "Z = jnp.asarray([3], jnp.int32)\n"
    )})
    msgs = [f.message for f in findings_for(root, "dtype-discipline")]
    assert sum("dtype-less" in m for m in msgs) == 2, msgs


def test_dtype_discipline_fires_on_unpinned_accumulation(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/ops/mod.py": (
        "import jax\nimport jax.numpy as jnp\n"
        "def f(a, b):\n"
        "    x = jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())))\n"
        "    y = jnp.einsum('ij,jk->ik', a, b)\n"
        "    return x + y\n"
    )})
    msgs = [f.message for f in findings_for(root, "dtype-discipline")]
    assert any("dot_general without preferred_element_type" in m
               for m in msgs), msgs
    assert any("einsum without precision" in m for m in msgs), msgs


def test_dtype_discipline_fires_on_unanchored_softmax(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/ops/mod.py": (
        "import jax.numpy as jnp\n"
        "def bad(x):\n"
        "    return jnp.exp(x - x.max())\n"
        "def good(x):\n"
        "    return jnp.exp(x.astype(jnp.float32))\n"
    )})
    msgs = [f.message for f in findings_for(root, "dtype-discipline")]
    assert any("bad() computes exp/softmax" in m for m in msgs), msgs
    assert not any("good()" in m for m in msgs), msgs


def test_dtype_discipline_fires_on_inline_sentinel(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/ops/mod.py": (
        "import jax.numpy as jnp\n"
        "NEG = -1e30\n"
        "ANN: float = -1e30\n"  # annotated module constant: also allowed
        "def f(x, mask):\n"
        "    return jnp.where(mask, x, -1e30)\n"
    )})
    findings = findings_for(root, "dtype-discipline")
    assert len(findings) == 1 and "inline mask sentinel" in \
        findings[0].message, findings
    assert findings[0].line == 5


def test_dtype_discipline_fires_on_unpaired_quantpages_data(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/ops/mod.py": (
        "from gridllm_tpu.ops.kvcache import QuantPages\n"
        "def bad(p):\n"
        "    if isinstance(p, QuantPages):\n"
        "        return p.data\n"
        "    return p\n"
        "def good(p):\n"
        "    if isinstance(p, QuantPages):\n"
        "        return p.data, p.scale\n"
        "    return p\n"
    )})
    msgs = [f.message for f in findings_for(root, "dtype-discipline")]
    assert any("bad() consumes QuantPages p.data" in m for m in msgs), msgs
    assert not any("good()" in m for m in msgs), msgs


def test_dtype_discipline_waiver(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/ops/mod.py": (
        "import jax.numpy as jnp\n"
        "X = jnp.asarray([1, 2])  # dtype-ok\n"
    )})
    assert findings_for(root, "dtype-discipline") == []


# -- host-sync-discipline (gridcheck v3) ------------------------------------

_FIXTURE_ENGINE_LOOPS = (
    "import numpy as np\n"
    "import jax\n"
    "class Engine:\n"
    "    def _ingest_block(self, out):\n"
    "        raw = np.asarray(jax.device_get(out))\n"
    "        return raw\n"
    "    def _dispatch_block(self, k):\n"
    "        return int(self.tokens[0])\n"
    "    def _fetch_oldest(self):\n"
    "        return np.asarray(self.x)  # sync-ok\n"
    "    def helper(self):\n"
    "        return self.y.item()\n"
)


def test_host_sync_fires_inside_loop_functions(tmp_path):
    root = make_repo(tmp_path,
                     {"gridllm_tpu/engine/engine.py": _FIXTURE_ENGINE_LOOPS})
    findings = findings_for(root, "host-sync-discipline")
    msgs = [f.message for f in findings]
    assert any("_ingest_block" in m and "np.asarray" in m for m in msgs), msgs
    assert any("_ingest_block" in m and "device_get" in m for m in msgs), msgs
    assert any("_dispatch_block" in m and "int()" in m for m in msgs), msgs
    # the declared sync point and the out-of-scope helper are exempt
    assert not any("inside _fetch_oldest()" in m for m in msgs), msgs
    assert not any("helper" in m for m in msgs), msgs


def test_host_sync_flags_stale_waiver(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/engine/engine.py": (
        "class Engine:\n"
        "    def _ingest_block(self, out):\n"
        "        x = 1  # sync-ok\n"
        "        return x\n"
    )})
    msgs = [f.message for f in findings_for(root, "host-sync-discipline")]
    assert any("stale waiver" in m for m in msgs), msgs


def test_host_sync_item_and_block_until_ready(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/engine/engine.py": (
        "class Engine:\n"
        "    def step(self):\n"
        "        v = self.out.item()\n"
        "        self.out.block_until_ready()\n"
        "        return v\n"
    )})
    msgs = [f.message for f in findings_for(root, "host-sync-discipline")]
    assert any(".item()" in m for m in msgs), msgs
    assert any("block_until_ready" in m for m in msgs), msgs


# -- helpers ----------------------------------------------------------------

def test_expand_braces():
    assert expand_braces("gridllm_a_total") == ["gridllm_a_total"]
    assert expand_braces("gridllm_kv_{used,free}") == [
        "gridllm_kv_used", "gridllm_kv_free"]
    assert expand_braces("gridllm_{a,b}_x_{c,d}") == [
        "gridllm_a_x_c", "gridllm_a_x_d", "gridllm_b_x_c", "gridllm_b_x_d"]


def test_readme_table_metrics_parses_rows_only():
    doc = ("prose gridllm_not_in_table\n"
           "| `gridllm_engine_kv_pages_{used,free}` (model) | pressure |\n")
    names = readme_table_metrics(doc)
    assert set(names) == {"gridllm_engine_kv_pages_used",
                          "gridllm_engine_kv_pages_free"}


# -- the actual gate --------------------------------------------------------

def test_self_run_is_clean():
    """Zero findings from exactly 13 registered rules over this repo:
    the invariant set the analyzer encodes HOLDS, and stays held — any
    regression fails here (and in the tier-1 static-analysis CI job)
    with a file:line reason. The rule-count pin makes a silently
    dropped rule module a failure too, not a quieter analyzer."""
    from gridllm_tpu.analysis import RULES, load_rules

    findings = run(REPO_ROOT)
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)
    load_rules()
    assert len(RULES) == 13, sorted(RULES)


def test_cli_exit_codes_and_json(tmp_path):
    env_table = _full_env_table()
    bad = make_repo(tmp_path / "bad", {"gridllm_tpu/mod.py": (
        "import os\nX = os.environ.get('GRIDLLM_PALLAS')\n")})
    proc = subprocess.run(
        [sys.executable, "-m", "gridllm_tpu.analysis", "--strict", "--json",
         "--root", str(bad)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["version"] == "gridllm-analysis/v1"
    assert any(f["rule"] == "config-discipline"
               for f in payload["findings"])

    clean = make_repo(tmp_path / "clean", {
        "README.md": env_table +
            "\n| `gridllm_ok_total` (model) | fixture metric |\n",
        "gridllm_tpu/engine/engine.py": (
            "from gridllm_tpu.obs import default_registry\n"
            "C = default_registry().counter(\n"
            "    'gridllm_ok_total', 'Fixture.', ('model',))\n"
        ),
    })
    proc = subprocess.run(
        [sys.executable, "-m", "gridllm_tpu.analysis", "--strict",
         "--root", str(clean)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
