"""Analyzer tests (ISSUE 8): every rule must fire on a seeded violation
(a checker that cannot fail is waiving the policy silently), and the
self-run over THIS repo must be clean — that second half is the actual
invariant gate tier-1 runs.

Fixture repos are tiny synthetic trees in tmp_path; rules are exercised
through the same ``run()`` entry the CLI uses.
"""

import json
import subprocess
import sys
from pathlib import Path

from gridllm_tpu.analysis import run
from gridllm_tpu.analysis.rules.dashboard_drift import (
    expand_braces,
    readme_table_metrics,
)

REPO_ROOT = Path(__file__).resolve().parents[1]

# a README configuration table covering every registered env var, so
# fixture repos only trip the violations they seed (generated, not typed)
def _full_env_table() -> str:
    from gridllm_tpu.utils.config import ENV_VARS

    rows = ["## Configuration", "",
            "| Variable | Default | Description |", "|---|---|---|"]
    rows += [f"| `{v.name}` | `{v.default}` | {v.description} |"
             for v in ENV_VARS.values()]
    return "\n".join(rows)


def make_repo(tmp_path: Path, files: dict[str, str]) -> Path:
    defaults = {
        "README.md": _full_env_table() + "\n",
        "gridllm_tpu/__init__.py": "",
        "deploy/grafana-dashboard.json": "{}",
        "deploy/prometheus-alerts.yml": "groups: []",
    }
    for rel, text in {**defaults, **files}.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    return tmp_path


def findings_for(root: Path, rule: str):
    return [f for f in run(root, [rule]) if f.rule == rule]


# -- per-rule seeded violations --------------------------------------------

def test_config_discipline_fires_on_direct_read(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/mod.py": (
        "import os\n"
        "LEVEL = os.environ.get('GRIDLLM_LOG_LEVEL', 'info')\n"
    )})
    msgs = [f.message for f in findings_for(root, "config-discipline")]
    assert any("direct os.environ read of GRIDLLM_LOG_LEVEL" in m
               for m in msgs), msgs


def test_config_discipline_fires_on_unregistered_var(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/mod.py": (
        "from gridllm_tpu.utils.config import env_str\n"
        "X = env_str('GRIDLLM_NO_SUCH_KNOB')\n"
    )})
    msgs = [f.message for f in findings_for(root, "config-discipline")]
    assert any("GRIDLLM_NO_SUCH_KNOB" in m and "ENV_VARS" in m
               for m in msgs), msgs


def test_config_discipline_fires_on_readme_drift(tmp_path):
    # README documents a var the registry does not know
    root = make_repo(tmp_path, {"README.md": _full_env_table() + (
        "\n| `GRIDLLM_GHOST_KNOB` | `1` | not registered anywhere |\n")})
    msgs = [f.message for f in findings_for(root, "config-discipline")]
    assert any("GRIDLLM_GHOST_KNOB" in m and "not registered" in m
               for m in msgs), msgs


def test_config_discipline_fires_on_default_drift(tmp_path):
    # README documents a default that disagrees with the registry
    table = _full_env_table().replace(
        "| `GRIDLLM_MAX_BATCH_SLOTS` | `8` |",
        "| `GRIDLLM_MAX_BATCH_SLOTS` | `16` |")
    assert "| `16` |" in table, "fixture assumes the registry default is 8"
    root = make_repo(tmp_path, {"README.md": table + "\n"})
    msgs = [f.message for f in findings_for(root, "config-discipline")]
    assert any("GRIDLLM_MAX_BATCH_SLOTS" in m and "default" in m
               for m in msgs), msgs


def test_lock_discipline_fires_on_unguarded_mutation(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/engine_like.py": (
        "class E:\n"
        "    def bad(self, slot):\n"
        "        self.alloc.free(slot)\n"
        "    def good(self, slot):\n"
        "        with self._alloc_lock:\n"
        "            self.alloc.free(slot)\n"
    )})
    fs = findings_for(root, "lock-discipline")
    assert len(fs) == 1 and fs[0].line == 3, fs


def test_lock_discipline_fires_on_order_inversion(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/engine_like.py": (
        "class E:\n"
        "    def inverted(self):\n"
        "        with self.dispatch_lock:\n"
        "            with self._alloc_lock:\n"
        "                pass\n"
        "    def single_stmt_inverted(self):\n"
        "        with self.dispatch_lock, self._alloc_lock:\n"
        "            pass\n"
        "    def correct(self):\n"
        "        with self._alloc_lock, self.dispatch_lock:\n"
        "            pass\n"
        "    def also_correct(self):\n"
        "        with self._alloc_lock:\n"
        "            with self.dispatch_lock:\n"
        "                pass\n"
    )})
    fs = findings_for(root, "lock-discipline")
    assert sorted(f.line for f in fs) == [4, 7], fs


def test_dashboard_drift_fires_on_phantom_panel_metric(tmp_path):
    root = make_repo(tmp_path, {
        "gridllm_tpu/m.py": (
            "from gridllm_tpu.obs import default_registry\n"
            "C = default_registry().counter(\n"
            "    'gridllm_real_total', 'Real.', ('model',))\n"
        ),
        "deploy/grafana-dashboard.json":
            '{"expr": "rate(gridllm_phantom_total[5m])"}',
        "README.md": _full_env_table() +
            "\n| `gridllm_real_total` (model) | real |\n",
    })
    msgs = [f.message for f in findings_for(root, "dashboard-drift")]
    assert any("gridllm_phantom_total" in m and "no code registers" in m
               for m in msgs), msgs


def test_dashboard_drift_fires_on_undocumented_metric(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/m.py": (
        "from gridllm_tpu.obs import default_registry\n"
        "C = default_registry().counter(\n"
        "    'gridllm_undocumented_total', 'Help.', ('model',))\n"
    )})
    msgs = [f.message for f in findings_for(root, "dashboard-drift")]
    assert any("gridllm_undocumented_total" in m
               and "README metrics table" in m for m in msgs), msgs


def test_dashboard_drift_fires_on_wrong_suffix(tmp_path):
    # a counter referenced with a histogram-only series suffix
    root = make_repo(tmp_path, {
        "gridllm_tpu/m.py": (
            "from gridllm_tpu.obs import default_registry\n"
            "C = default_registry().counter(\n"
            "    'gridllm_real_total', 'Real.', ('model',))\n"
        ),
        "deploy/prometheus-alerts.yml":
            "expr: gridllm_real_total_bucket > 0",
        "README.md": _full_env_table() +
            "\n| `gridllm_real_total` (model) | real |\n",
    })
    msgs = [f.message for f in findings_for(root, "dashboard-drift")]
    assert any("gridllm_real_total_bucket" in m for m in msgs), msgs


def test_dashboard_drift_fires_on_bare_histogram_family_in_query(tmp_path):
    # a Grafana QUERY naming the family references a series that never
    # exists (only _bucket/_sum/_count are exported) — flat-panel drift.
    # The same family name in prose (title) stays legal.
    root = make_repo(tmp_path, {
        "gridllm_tpu/m.py": (
            "from gridllm_tpu.obs import default_registry\n"
            "H = default_registry().histogram(\n"
            "    'gridllm_lat_seconds', 'Latency.')\n"
        ),
        "deploy/grafana-dashboard.json": (
            '{"title": "gridllm_lat_seconds p95",\n'
            ' "expr": "histogram_quantile(0.95, rate(gridllm_lat_seconds[5m]))"}'
        ),
        "README.md": _full_env_table() +
            "\n| `gridllm_lat_seconds` | latency |\n",
    })
    fs = [f for f in findings_for(root, "dashboard-drift")
          if "histogram family" in f.message]
    assert len(fs) == 1 and fs[0].line == 2, fs


def test_jit_discipline_fires_on_unwrapped_and_dirty_bodies(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/engine/engine.py": (
        "import jax\n"
        "from functools import partial\n"
        "class InferenceEngine:\n"
        "    def _build_fns(self):\n"
        "        @partial(jax.jit, static_argnames=('k',))\n"
        "        def unwrapped_fn(params, toks, k):\n"
        "            if k:\n"                      # static: fine
        "                n = toks.sum().item()\n"  # .item() inside jit
        "            if toks > 0:\n"               # traced branch
        "                pass\n"
        "            if params is None:\n"         # structure check: fine
        "                pass\n"
        "            return toks\n"
        "        self._fn = jax.jit(lambda p: p)\n"  # inline, unwrapped
        "        @partial(jax.jit)\n"
        "        def wrapped_fn(x):\n"
        "            return x\n"
        "        self._ok = self.perf.wrap('ok', wrapped_fn)\n"
    )})
    msgs = [f.message for f in findings_for(root, "jit-discipline")]
    assert any("unwrapped_fn" in m and "perf.wrap" in m for m in msgs), msgs
    assert any(".item()" in m for m in msgs), msgs
    assert any("traced value" in m and "toks" in m for m in msgs), msgs
    assert any("inline jax.jit" in m for m in msgs), msgs
    assert not any(m.startswith("jitted function wrapped_fn(")
                   for m in msgs), msgs
    assert not any("params" in m and "traced" in m for m in msgs), msgs


def test_span_pairing_fires_on_leaky_span(tmp_path):
    root = make_repo(tmp_path, {"gridllm_tpu/svc.py": (
        "class S:\n"
        "    def leaky(self, rid):\n"
        "        span = self.tracer.begin(rid, 'x')\n"
        "        self.work()\n"
        "        self.tracer.end(span)\n"        # not in a finally
        "    def dropped(self, rid):\n"
        "        self.tracer.begin(rid, 'x')\n"  # discarded outright
        "    def safe(self, rid):\n"
        "        span = self.tracer.begin(rid, 'x')\n"
        "        try:\n"
        "            self.work()\n"
        "        finally:\n"
        "            self.tracer.end(span)\n"
        "    def handoff(self, rid):\n"
        "        self._spans[rid] = self.tracer.begin(rid, 'x')\n"
    )})
    fs = findings_for(root, "span-pairing")
    assert sorted(f.line for f in fs) == [3, 7], fs


def test_span_pairing_fires_when_try_does_not_cover_begin(tmp_path):
    # an end()-in-finally elsewhere in the function must not count when a
    # statement between begin() and the try can raise with the span open
    root = make_repo(tmp_path, {"gridllm_tpu/svc.py": (
        "class S:\n"
        "    def gap(self, rid):\n"
        "        span = self.tracer.begin(rid, 'x')\n"
        "        self.prep()\n"              # raises -> span leaks
        "        try:\n"
        "            self.work()\n"
        "        finally:\n"
        "            self.tracer.end(span)\n"
        "    def begin_inside_try(self, rid):\n"
        "        try:\n"
        "            span = self.tracer.begin(rid, 'x')\n"
        "            self.work()\n"
        "        finally:\n"
        "            self.tracer.end(span)\n"
    )})
    fs = findings_for(root, "span-pairing")
    assert sorted(f.line for f in fs) == [3], fs


def test_config_discipline_other_tables_do_not_satisfy_doc_check(tmp_path):
    # drop one var's Configuration-table row but mention it in another
    # markdown table: the doc check must still fire
    table = _full_env_table()
    lines = [l for l in table.splitlines() if "GRIDLLM_PALLAS" not in l]
    readme = "\n".join(lines) + (
        "\n\n## Metrics\n"
        "| `gridllm_kernel_dispatch_total` | per GRIDLLM_PALLAS policy |\n")
    root = make_repo(tmp_path, {"README.md": readme})
    msgs = [f.message for f in findings_for(root, "config-discipline")]
    assert any("GRIDLLM_PALLAS" in m and "missing from the README" in m
               for m in msgs), msgs


def test_metric_hygiene_audits_keyword_labelnames(tmp_path):
    root = make_repo(tmp_path, {
        "gridllm_tpu/m.py": (
            "from gridllm_tpu.obs import default_registry\n"
            "A = default_registry().counter(\n"
            "    'gridllm_kw_total', 'Kw.', labelnames=('request_id',))\n"
            "B = default_registry().counter(\n"
            "    'gridllm_splat_total', 'Splat.', **extra)\n"
        ),
        "README.md": _full_env_table() +
            "\n| `gridllm_kw_total` `gridllm_splat_total` | seeded |\n",
    })
    msgs = [f.message for f in findings_for(root, "metric-hygiene")]
    assert any("gridllm_kw_total" in m and "request_id" in m
               for m in msgs), msgs
    assert any("gridllm_splat_total" in m and "audited" in m
               for m in msgs), msgs


def test_metric_hygiene_fires_on_bad_name_label_help(tmp_path):
    root = make_repo(tmp_path, {
        "gridllm_tpu/m.py": (
            "from gridllm_tpu.obs import default_registry\n"
            "A = default_registry().counter(\n"
            "    'BadName_total', 'Bad name.')\n"
            "B = default_registry().counter(\n"
            "    'gridllm_leaky_total', 'Bad label.', ('job_id',))\n"
            "C = default_registry().counter(\n"
            "    'gridllm_helpless_total', '')\n"
        ),
        "README.md": _full_env_table() +
            "\n| `BadName_total` `gridllm_leaky_total` "
            "`gridllm_helpless_total` | seeded |\n",
    })
    msgs = [f.message for f in findings_for(root, "metric-hygiene")]
    assert any("BadName_total" in m and "naming" in m for m in msgs), msgs
    assert any("job_id" in m for m in msgs), msgs
    assert any("gridllm_helpless_total" in m and "help" in m
               for m in msgs), msgs


# -- helpers ----------------------------------------------------------------

def test_expand_braces():
    assert expand_braces("gridllm_a_total") == ["gridllm_a_total"]
    assert expand_braces("gridllm_kv_{used,free}") == [
        "gridllm_kv_used", "gridllm_kv_free"]
    assert expand_braces("gridllm_{a,b}_x_{c,d}") == [
        "gridllm_a_x_c", "gridllm_a_x_d", "gridllm_b_x_c", "gridllm_b_x_d"]


def test_readme_table_metrics_parses_rows_only():
    doc = ("prose gridllm_not_in_table\n"
           "| `gridllm_engine_kv_pages_{used,free}` (model) | pressure |\n")
    names = readme_table_metrics(doc)
    assert set(names) == {"gridllm_engine_kv_pages_used",
                          "gridllm_engine_kv_pages_free"}


# -- the actual gate --------------------------------------------------------

def test_self_run_is_clean():
    """Zero findings over this repo: the invariant set the analyzer
    encodes HOLDS, and stays held — any regression fails here (and in
    the tier-1 static-analysis CI job) with a file:line reason."""
    findings = run(REPO_ROOT)
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


def test_cli_exit_codes_and_json(tmp_path):
    env_table = _full_env_table()
    bad = make_repo(tmp_path / "bad", {"gridllm_tpu/mod.py": (
        "import os\nX = os.environ.get('GRIDLLM_PALLAS')\n")})
    proc = subprocess.run(
        [sys.executable, "-m", "gridllm_tpu.analysis", "--strict", "--json",
         "--root", str(bad)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["version"] == "gridllm-analysis/v1"
    assert any(f["rule"] == "config-discipline"
               for f in payload["findings"])

    clean = make_repo(tmp_path / "clean", {
        "README.md": env_table +
            "\n| `gridllm_ok_total` (model) | fixture metric |\n",
        "gridllm_tpu/engine/engine.py": (
            "from gridllm_tpu.obs import default_registry\n"
            "C = default_registry().counter(\n"
            "    'gridllm_ok_total', 'Fixture.', ('model',))\n"
        ),
    })
    proc = subprocess.run(
        [sys.executable, "-m", "gridllm_tpu.analysis", "--strict",
         "--root", str(clean)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
