"""Run the differential shape gate (tests/integration/differential.py, the
port of the reference's integration.ts harness) against an in-process live
cluster — same script CI runs against the docker bundle."""

import asyncio
import threading

from aiohttp import web

from gridllm_tpu.bus.memory import InMemoryBus
from gridllm_tpu.engine import EngineConfig, InferenceEngine
from gridllm_tpu.gateway.app import create_app
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.config import Config, WorkerConfig
from gridllm_tpu.worker.service import WorkerService

from .integration.differential import run as run_differential


async def test_differential_shape_gate():
    engine = InferenceEngine(EngineConfig(
        model="tiny-llama", max_slots=2, page_size=8, num_pages=64,
        max_pages_per_slot=16, prefill_buckets=(64,), seed=0,
    ))
    bus = InMemoryBus()
    await bus.connect()
    config = Config()
    registry = WorkerRegistry(bus, config.scheduler)
    scheduler = JobScheduler(bus, registry, config.scheduler)
    await registry.initialize()
    await scheduler.initialize()
    app = create_app(bus, registry, scheduler, config)
    worker = WorkerService(bus, {"tiny-llama": engine}, WorkerConfig(),
                           stream_flush_ms=5)
    await worker.start()
    await asyncio.sleep(0.2)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]

    # differential.py uses blocking urllib — run it off the event loop
    ok = await asyncio.to_thread(
        run_differential, f"http://127.0.0.1:{port}", "tiny-llama", None
    )
    await runner.cleanup()
    await worker.stop()
    await scheduler.shutdown()
    assert ok, "API shape diverged from the recorded Ollama/OpenAI goldens"
