"""Child process for tests/test_disagg.py: a REAL worker (tiny-llama
engine + WorkerService) with a fleet role, over a RESP broker — one
prefill child + one decode child make a two-process disaggregated fleet.

Usage: python disagg_worker_child.py <broker_port> <worker_id> <role>

Engines are seeded identically everywhere (random-init weights come from
PRNGKey(0)), so token streams compare bit-for-bit across processes.
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("GRIDLLM_KVX_CHUNK_BYTES", "2048")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


async def main() -> None:
    broker_port, worker_id, role = sys.argv[1], sys.argv[2], sys.argv[3]
    from gridllm_tpu.bus import create_bus
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.utils.config import WorkerConfig
    from gridllm_tpu.worker.service import WorkerService

    eng = InferenceEngine(EngineConfig(
        model="tiny-llama", max_slots=2, page_size=8, num_pages=96,
        max_pages_per_slot=16, prefill_buckets=(16, 64, 128),
        prefill_chunk=16, seed=42,
    ))
    bus = create_bus(f"resp://127.0.0.1:{broker_port}")
    await bus.connect()
    svc = WorkerService(
        bus, {"tiny-llama": eng},
        WorkerConfig(worker_id=worker_id, role=role,
                     heartbeat_interval_ms=150,
                     resource_monitor_interval_ms=500),
        stream_flush_ms=5,
    )
    await svc.start()
    print("CHILD_READY", flush=True)
    await asyncio.Event().wait()  # run until killed


asyncio.run(main())
