"""Performance-introspection tests (ISSUE 4): recompile tripwire
semantics (steady-state decode is recompile-free; an unseen shape bucket
counts exactly once with the right labels and a flight-recorder event),
device-memory accounting math on the CPU backend, the /admin/memory and
/admin/profile endpoints, profiler-capture lifecycle, bench record
comparison, and the jsonmask experimental/import-clean satellite."""

import importlib
import json
import os
import time

import pytest

from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine
from gridllm_tpu.obs import (
    CaptureBusy,
    ProfilerCapture,
    default_flight_recorder,
    memory_snapshot,
    register_memory_probe,
    unregister_memory_probe,
)
from gridllm_tpu.obs.perf import RECOMPILES_TOTAL, recompile_totals

TINY = dict(
    model="tiny-llama",
    max_slots=4,
    page_size=8,
    num_pages=64,
    max_pages_per_slot=8,
    prefill_buckets=(16, 32),
)

OPTS = {"temperature": 0.0, "num_predict": 6}


@pytest.fixture(scope="module")
def engine():
    eng = InferenceEngine(EngineConfig(**TINY))
    # warm + arm: the first naturally completed request flips the
    # tripwire to steady state (engine._finish)
    eng.generate(GenerationRequest(id="warm", prompt="hi", options=OPTS))
    assert eng.perf.armed
    return eng


# ---------------------------------------------------------------------------
# recompile tripwire
# ---------------------------------------------------------------------------


def test_steady_state_varying_batch_fill_zero_recompiles(engine):
    """Continuous batching varies ACTIVE slots, not shapes: decoding with
    1, 2, and 3 concurrent requests in an already-seen bucket must not
    compile anything new."""
    before = recompile_totals()["steady"]
    done = []
    for n in (1, 2, 3):
        for i in range(n):
            engine.submit(GenerationRequest(
                id=f"fill{n}-{i}", prompt="hi",
                options=OPTS,
                on_chunk=lambda d, fin, res: fin and done.append(res)))
        while len(done) < sum((1, 2, 3)[: (1, 2, 3).index(n) + 1]):
            engine.step()
    assert recompile_totals()["steady"] == before


def test_unseen_shape_bucket_counts_exactly_one(engine):
    """A prompt landing in a bucket never prefilled before compiles ONE
    new program: counted under {fn="prefill", reason="new_shape"} with a
    flight-recorder event carrying the offending shapes."""
    before = RECOMPILES_TOTAL.value(fn="prefill", reason="new_shape")
    steady_before = recompile_totals()["steady"]
    long_prompt = "x" * 24  # > bucket 16, pads to bucket 32
    engine.generate(GenerationRequest(id="bkt", prompt=long_prompt,
                                      options=OPTS))
    assert RECOMPILES_TOTAL.value(
        fn="prefill", reason="new_shape") == before + 1
    # exactly one steady recompile total — decode/sampler shapes are
    # bucket-independent and must NOT have recompiled
    assert recompile_totals()["steady"] == steady_before + 1
    events = [e for e in default_flight_recorder().snapshot()
              ["rings"].get("engine", [])
              if e["event"] == "recompile"]
    assert events, "steady-state recompile must leave a flight event"
    last = events[-1]
    assert last["fn"] == "prefill" and last["reason"] == "new_shape"
    assert "32" in last["shapes"]  # the offending padded bucket

    # repeat of the SAME bucket: no further count
    engine.generate(GenerationRequest(id="bkt2", prompt="y" * 24,
                                      options=OPTS))
    assert RECOMPILES_TOTAL.value(
        fn="prefill", reason="new_shape") == before + 1


def test_static_arg_change_classified_new_static(engine):
    """decode_block's fused step count k is a static jit arg — a never-
    seen k recompiles with reason new_static, not new_shape."""
    # baseline signature first: a spec-on engine (ISSUE 5 default) serves
    # via the verify program and never compiles decode_block during
    # warmup, and a probe's very FIRST signature always classifies as
    # warmup — so establish k=1 (a no-op when spec is off: the runner
    # already compiled it) before probing the static change
    engine._dispatch_block(1)
    engine._inflight.clear()   # no slots are active; tokens are junk
    before = RECOMPILES_TOTAL.value(fn="decode_block", reason="new_static")
    engine._dispatch_block(3)  # k=3 never dispatched by these tests
    engine._inflight.clear()
    assert RECOMPILES_TOTAL.value(
        fn="decode_block", reason="new_static") == before + 1


# ---------------------------------------------------------------------------
# device-memory accounting
# ---------------------------------------------------------------------------


def test_memory_snapshot_sums_and_kv_math(engine):
    import jax

    register_memory_probe("test-perf", lambda: {
        "tiny-llama": engine.memory_arrays()})
    try:
        snap = memory_snapshot()
    finally:
        unregister_memory_probe("test-perf")
    # per-device: the three kinds must sum to the measured live total
    # (acceptance: within 5% of reported device memory on CPU)
    assert snap["devices"], "no devices attributed"
    for label, dev in snap["devices"].items():
        total = dev["weightsBytes"] + dev["kvPoolBytes"] + dev["workspaceBytes"]
        assert total == pytest.approx(dev["totalLiveBytes"], rel=0.05)
    m = snap["models"]["tiny-llama"]
    # weights attribution matches the params tree exactly
    params_bytes = sum(a.nbytes for a in jax.tree_util.tree_leaves(
        engine.params) if hasattr(a, "nbytes"))
    assert m["weightsBytes"] == params_bytes
    # KV pool bytes = k + v + page table + lengths
    cache = engine.cache
    assert m["kvPoolBytes"] == (cache.k.nbytes + cache.v.nbytes
                                + cache.page_table.nbytes
                                + cache.lengths.nbytes)
    # page accounting closes: used + cached + free == num_pages
    assert (m["pagesUsed"] + m["pagesCached"] + m["pagesFree"]
            == TINY["num_pages"])
    assert m["bytesPerPage"] * TINY["num_pages"] == (
        cache.k.nbytes + cache.v.nbytes)
    # idle engine: nothing live, no fragmentation
    assert m["liveTokens"] == 0 and m["fragmentation"] == 0.0


def test_memory_fragmentation_counts_reserved_capacity(engine):
    """Mid-decode, pages are reserved up to the request's capacity; the
    fragmentation estimate is the not-yet-written share of that."""
    register_memory_probe("test-perf2", lambda: {
        "tiny-llama": engine.memory_arrays()})
    try:
        engine.submit(GenerationRequest(
            id="frag", prompt="hello", options={"temperature": 0.0,
                                                "num_predict": 20}))
        engine.step()  # admit + first decode step
        m = memory_snapshot()["models"]["tiny-llama"]
        assert m["pagesUsed"] > 0
        assert m["liveTokens"] > 0
        assert 0 < m["fragmentation"] < 1
        # drain so the module-scoped engine is idle for later tests
        while engine.step():
            pass
    finally:
        unregister_memory_probe("test-perf2")


async def test_admin_memory_endpoint(engine):
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.bus.memory import InMemoryBus
    from gridllm_tpu.gateway.app import create_app
    from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
    from gridllm_tpu.utils.config import Config

    from .helpers import fast_config

    bus = InMemoryBus(key_prefix="G:")
    await bus.connect()
    cfg = fast_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    app = create_app(bus, registry, scheduler, Config(scheduler=cfg))
    client = TestClient(TestServer(app))
    await client.start_server()
    register_memory_probe("test-perf3", lambda: {
        "tiny-llama": engine.memory_arrays()})
    try:
        resp = await client.get("/admin/memory")
        assert resp.status == 200
        body = await resp.json()
        assert "tiny-llama" in body["models"]
        dev = next(iter(body["devices"].values()))
        assert dev["weightsBytes"] > 0
        # the gauges render from the same snapshot path
        metrics = await client.get("/metrics")
        text = await metrics.text()
        assert 'gridllm_device_memory_bytes{device="cpu:0",kind="weights"}' \
            in text
    finally:
        unregister_memory_probe("test-perf3")
        await client.close()
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()


# ---------------------------------------------------------------------------
# step-time decomposition
# ---------------------------------------------------------------------------


def test_step_decomposition_histograms_populate():
    from gridllm_tpu.obs.perf import (
        DEVICE_STEP_SECONDS,
        DISPATCH_SECONDS,
        HOST_SCHED_SECONDS,
    )

    eng = InferenceEngine(EngineConfig(**TINY, decode_block=2,
                                       pipeline_depth=2))
    model = "tiny-llama"
    d0 = DISPATCH_SECONDS.count(model=model)
    v0 = DEVICE_STEP_SECONDS.count(model=model)
    h0 = HOST_SCHED_SECONDS.count(model=model)
    eng.start()
    try:
        eng.generate(GenerationRequest(id="dec", prompt="hello",
                                       options={"temperature": 0.0,
                                                "num_predict": 12}))
    finally:
        eng.stop()
    assert DISPATCH_SECONDS.count(model=model) > d0
    assert DEVICE_STEP_SECONDS.count(model=model) > v0
    # host-sched gap is recorded between consecutive runner iterations
    assert HOST_SCHED_SECONDS.count(model=model) > h0


# ---------------------------------------------------------------------------
# profiler capture
# ---------------------------------------------------------------------------


def _wait_capture_done(prof, timeout=60.0):
    """jax.profiler.stop_trace serializes metadata for EVERY module the
    process ever compiled — after kernel-heavy test files it can take
    tens of seconds (by design it runs in the capture's daemon thread,
    never on the caller). Tests must wait it out, not race it."""
    deadline = time.time() + timeout
    while prof.active is not None and time.time() < deadline:
        time.sleep(0.05)
    assert prof.active is None, "profiler capture never finished flushing"


@pytest.mark.slow  # 3 captures × multi-second stop_trace flushes — the
# tier-1 budget can't afford them; the endpoint and watchdog tests keep
# one capture+flush each in the fast gate
def test_profiler_capture_lifecycle(tmp_path):
    from gridllm_tpu.obs import default_profiler

    # one jax profiler per process: an earlier test's singleton capture
    # (e.g. a watchdog auto-capture) must fully flush before this local
    # manager may start_trace
    _wait_capture_done(default_profiler())
    prof = ProfilerCapture(base_dir=str(tmp_path), keep=2)
    info = prof.capture(0.15, reason="unit test/odd")
    assert info["path"].startswith(str(tmp_path))
    assert os.path.isdir(info["path"])
    assert "/" not in os.path.basename(info["path"]).replace("trace-", "", 1)
    with pytest.raises(CaptureBusy):
        prof.capture(0.1)
    _wait_capture_done(prof)
    assert prof.captures and prof.captures[-1]["path"] == info["path"]
    # the trace actually wrote something (jax profiler plugin dirs)
    assert any(os.scandir(info["path"]))
    # pruning: keep=2 bounds the artifact dir (3 captures total > keep;
    # each flush costs real seconds in a compile-heavy process, so keep
    # the count minimal)
    for _ in range(2):
        prof.capture(0.01)
        _wait_capture_done(prof)
    dirs = [e for e in os.scandir(tmp_path) if e.is_dir()]
    assert len(dirs) <= 2


async def test_admin_profile_endpoint(tmp_path, monkeypatch):
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.bus.memory import InMemoryBus
    from gridllm_tpu.gateway.app import create_app
    from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
    from gridllm_tpu.utils.config import Config

    from .helpers import fast_config

    monkeypatch.setenv("GRIDLLM_PROFILE_DIR", str(tmp_path))
    bus = InMemoryBus(key_prefix="G:")
    await bus.connect()
    cfg = fast_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    app = create_app(bus, registry, scheduler, Config(scheduler=cfg))
    client = TestClient(TestServer(app))
    await client.start_server()
    from gridllm_tpu.obs import default_profiler

    # a prior test's (or watchdog auto-) capture may still be flushing
    # the process-global profiler — wait for idle before asserting 200
    _wait_capture_done(default_profiler())
    try:
        resp = await client.post("/admin/profile?seconds=0.2")
        assert resp.status == 200
        body = await resp.json()
        assert body["path"].startswith(str(tmp_path))
        # a second capture while one runs is a 409, not a crash
        resp2 = await client.post("/admin/profile?seconds=0.2")
        assert resp2.status == 409
        resp3 = await client.post("/admin/profile?seconds=nope")
        assert resp3.status == 400
        _wait_capture_done(default_profiler())
    finally:
        await client.close()
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()


def test_watchdog_hang_capture(tmp_path, monkeypatch):
    """The decode-step hang path starts a short capture and attaches the
    artifact path to the diagnosis; profile_on_hang_s=0 disables."""
    from gridllm_tpu.obs import HangWatchdog, MetricsRegistry
    from gridllm_tpu.utils.config import WatchdogConfig

    class _Sched:
        metrics = MetricsRegistry()

    monkeypatch.setenv("GRIDLLM_PROFILE_DIR", str(tmp_path))
    from gridllm_tpu.obs import default_profiler

    _wait_capture_done(default_profiler())
    wd = HangWatchdog(_Sched(), WatchdogConfig(profile_on_hang_s=0.1))
    info = wd._profile_hang("decode-step")
    assert info is not None and info["path"].startswith(str(tmp_path))
    _wait_capture_done(default_profiler())
    wd_off = HangWatchdog(_Sched(), WatchdogConfig(profile_on_hang_s=0))
    assert wd_off._profile_hang("decode-step") is None


# ---------------------------------------------------------------------------
# bench record comparison (--emit / --compare)
# ---------------------------------------------------------------------------


def _rec(**metrics):
    return {"schema": "gridllm-bench/v1", "scenario": "generate",
            "model": "tiny-llama", "platform": "cpu", "metrics": metrics}


def test_compare_records_flags_both_directions():
    import bench

    old = _rec(tok_s=100.0, p50_ttft_ms=50.0, recompiles_steady=0)
    ok, _ = bench.compare_records(old, _rec(tok_s=95.0, p50_ttft_ms=54.0,
                                            recompiles_steady=0))
    assert ok == []
    regs, _ = bench.compare_records(old, _rec(tok_s=80.0, p50_ttft_ms=50.0,
                                              recompiles_steady=0))
    assert any("tok_s" in r for r in regs)
    regs, _ = bench.compare_records(old, _rec(tok_s=100.0, p50_ttft_ms=60.0,
                                              recompiles_steady=0))
    assert any("p50_ttft_ms" in r for r in regs)
    # recompiles have zero tolerance — 0 -> 1 is a regression outright
    regs, _ = bench.compare_records(old, _rec(tok_s=100.0, p50_ttft_ms=50.0,
                                              recompiles_steady=1))
    assert any("recompiles_steady" in r for r in regs)


def test_compare_records_skips_mismatched_runs():
    import bench

    old = _rec(tok_s=100.0)
    new = _rec(tok_s=10.0)
    new["platform"] = "tpu"
    regs, notes = bench.compare_records(old, new)
    assert regs == [] and any("mismatch" in n for n in notes)


def test_build_record_schema():
    import bench

    class _Args:
        model = "tiny-llama"
        requests, tokens, slots, prompt_len = 2, 8, 4, 20

    payload = {"value": 42.0, "platform": "cpu", "tok_s": 42.0,
               "p50_ttft_ms": 10.0, "degraded": False}
    r = {"perf": {"recompiles_steady": 0, "recompiles_warmup": 3,
                  "recompiles_by_fn": {}, "peak_hbm_bytes": 1024}}
    rec = bench.build_record("generate", _Args(), payload, r)
    assert rec["schema"] == bench.BENCH_SCHEMA
    assert rec["metrics"]["recompiles_steady"] == 0
    assert rec["metrics"]["peak_hbm_bytes"] == 1024
    assert rec["metrics"]["tok_s"] == 42.0
    json.dumps(rec)  # must be serializable as written


# ---------------------------------------------------------------------------
# jsonmask satellite: explicitly experimental, stays import-clean
# ---------------------------------------------------------------------------


def test_jsonmask_is_marked_experimental_and_import_clean():
    """engine/jsonmask.py is unwired groundwork (no sampler mask hook
    exists): its docstring must say so, and importing it must stay
    side-effect-free — no metrics registered, no jit, no engine imports —
    so it can never silently become load-bearing at collection time."""
    from gridllm_tpu.obs import default_registry

    reg = default_registry()
    with reg._lock:
        metrics_before = set(reg._metrics)
    mod = importlib.import_module("gridllm_tpu.engine.jsonmask")
    mod = importlib.reload(mod)
    assert "EXPERIMENTAL" in mod.__doc__ and "NOT INTEGRATED" in mod.__doc__
    with reg._lock:
        assert set(reg._metrics) == metrics_before
    # nothing in the package imports it: the guarantee must not be
    # assumed delivered anywhere in the serving path
    import subprocess
    import sys

    probe = subprocess.run(
        [sys.executable, "-c",
         "import sys; import gridllm_tpu.worker.service, "
         "gridllm_tpu.engine.engine, gridllm_tpu.ops.sampling; "
         "sys.exit(1 if 'gridllm_tpu.engine.jsonmask' in sys.modules "
         "else 0)"],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=240,
    )
    assert probe.returncode == 0, probe.stderr[-500:]
