"""Draft-model speculative decoding with tree verification (ISSUE 18).

Layers of pinning:

- topology units: parents/depths/ancestor-mask/bitmask construction;
- accept walk: spec_accept_tree's greedy root-to-leaf walk (chain
  descent, sibling rescue, path/bonus accounting);
- KV commit: commit_tree_path moves exactly the accepted path's rows —
  across page boundaries, never touching rows below the verify base
  (pinned prefix-cache pages), int8 pools bit-verbatim;
- attention: the tree-masked verify reference degenerates to the legacy
  chain trace for a chain topology, and the interpret-mode ragged
  kernel's tree leg matches the reference;
- stream parity: greedy streams are byte-identical tree-spec-on vs
  spec-off — solo, concurrent, warm prefix-cache replays, and
  mid-stream resume from the context watermark; seeded sampling stays
  deterministic;
- hygiene: zero steady-state recompiles with the tree armed; unknown /
  incompatible draft models fall back to n-gram instead of failing.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine
from gridllm_tpu.obs.perf import recompile_totals
from gridllm_tpu.ops import attention as A
from gridllm_tpu.ops import pallas_kernels as PK
from gridllm_tpu.ops.kvcache import PagedKVCache, QuantPages, commit_tree_path
from gridllm_tpu.ops.sampling import SamplingParams, spec_accept_tree
from gridllm_tpu.ops.spec import (
    DraftModelDrafter,
    tree_ancestor_bits,
    tree_ancestor_mask,
    tree_depths,
    tree_topology,
)

TINY = dict(
    model="tiny-llama",
    max_slots=4,
    page_size=8,
    num_pages=64,
    max_pages_per_slot=8,
    prefill_buckets=(16, 32),
)
REP_PROMPT = "ab ab ab ab ab ab"
REP_OPTS = {"temperature": 0.0, "repeat_penalty": 1.0, "num_predict": 24}


@pytest.fixture(scope="module")
def tree_on():
    # draft model == target config with the same fresh PRNGKey(0) init →
    # identical weights, so acceptance is near-ceiling and the parity
    # tests exercise deep accepted paths, not the fallback row
    return InferenceEngine(EngineConfig(
        **TINY, spec_decode=True, spec_k=3, draft_model="tiny-llama"))


@pytest.fixture(scope="module")
def spec_off():
    return InferenceEngine(EngineConfig(**TINY, spec_decode=False))


# ---------------------------------------------------------------------------
# topology units
# ---------------------------------------------------------------------------


def test_tree_topology_chain_plus_siblings():
    p = tree_topology(3, 2)
    assert p.tolist() == [-1, 0, 1, 2, 0]
    assert tree_depths(p).tolist() == [0, 1, 2, 3, 1]
    # width 1 = pure chain; k = 0 degenerates to the root alone
    assert tree_topology(3, 1).tolist() == [-1, 0, 1, 2]
    assert tree_topology(0, 4).tolist() == [-1]
    with pytest.raises(ValueError):
        tree_topology(-1, 2)
    with pytest.raises(ValueError):
        tree_topology(2, 0)


def test_ancestor_mask_construction():
    p = tree_topology(2, 3)  # [-1, 0, 1, 0, 0]
    anc = tree_ancestor_mask(p)
    want = np.array([
        [1, 0, 0, 0, 0],   # root: itself
        [1, 1, 0, 0, 0],   # chain 1: root + itself
        [1, 1, 1, 0, 0],   # chain 2: root + chain1 + itself
        [1, 0, 0, 1, 0],   # sibling: root + itself (NOT chain nodes)
        [1, 0, 0, 0, 1],
    ], bool)
    np.testing.assert_array_equal(anc, want)
    # bitmask packing: bit j of entry i == anc[i, j]
    bits = tree_ancestor_bits(p)
    for i in range(len(p)):
        for j in range(len(p)):
            assert bool((int(bits[i]) >> j) & 1) == bool(anc[i, j])
    with pytest.raises(ValueError):
        tree_ancestor_bits(np.asarray([-1] + list(range(33)), np.int32))


# ---------------------------------------------------------------------------
# accept walk (greedy)
# ---------------------------------------------------------------------------


def _greedy_params(s):
    return dataclasses.replace(
        SamplingParams.defaults(s),
        temperature=jnp.zeros((s,), jnp.float32),
        repeat_penalty=jnp.ones((s,), jnp.float32),
    )


def _walk(logits, node_tokens, parents, valid, vocab=16, W=8):
    s = logits.shape[0]
    return spec_accept_tree(
        jnp.asarray(logits), jnp.asarray(node_tokens), parents,
        jnp.asarray(valid), _greedy_params(s),
        jnp.zeros((s, vocab), jnp.int32), jnp.zeros((s, W), jnp.int32),
        jnp.zeros((s,), jnp.int32), jnp.ones((s,), bool), vocab)


def test_accept_tree_greedy_chain_walk():
    parents = tree_topology(2, 2)  # [-1, 0, 1, 0]
    n, S, V = len(parents), 2, 16
    logits = np.full((S, n, V), -10.0, np.float32)
    tgt = [(i * 2 + 3) % V for i in range(n)]
    for i in range(n):
        logits[:, i, tgt[i]] = 5.0
    nt = np.zeros((S, n), np.int32)
    nt[:, 1] = tgt[0]            # chain head matches both slots
    nt[0, 2] = tgt[1]            # slot 0 depth-2 matches
    nt[1, 2] = (tgt[1] + 1) % V  # slot 1 depth-2 misses
    nt[:, 3] = (tgt[0] + 5) % V  # sibling never reached (head accepted)
    out, path, n_emit, last, *_ = _walk(
        logits, nt, parents, np.ones((S, n), bool))
    out, path = np.asarray(out), np.asarray(path)
    assert np.asarray(n_emit).tolist() == [3, 2]
    # slot 0: both chain nodes + bonus; slot 1: head + correction
    assert out.T[0, :3].tolist() == [tgt[0], tgt[1], tgt[2]]
    assert out.T[1, :2].tolist() == [tgt[0], tgt[1]]
    # path names the node backing each committed position; 0 = no KV
    # (the final corrected/bonus token)
    assert path[0, :3].tolist() == [1, 2, 0]
    assert path[1, :2].tolist() == [1, 0]
    assert np.asarray(last).tolist() == [tgt[2], tgt[1]]


def test_accept_tree_sibling_rescues_rejected_head():
    parents = tree_topology(2, 2)
    n, V = len(parents), 16
    logits = np.full((1, n, V), -10.0, np.float32)
    logits[0, 0, 7] = 5.0   # root argmax = 7
    logits[0, 3, 9] = 5.0   # after the sibling node, argmax = 9
    nt = np.zeros((1, n), np.int32)
    nt[0, 1] = 5            # chain head misses
    nt[0, 3] = 7            # sibling carries the greedy token
    out, path, n_emit, _, *_ = _walk(logits, nt, parents,
                                     np.ones((1, n), bool))
    assert int(n_emit[0]) == 2
    assert np.asarray(out).T[0, :2].tolist() == [7, 9]
    # position base+1 is backed by the SIBLING's optimistic row (node 3)
    assert np.asarray(path)[0, :2].tolist() == [3, 0]


def test_accept_tree_respects_node_validity():
    """A matching token on an INVALID node must not be accepted — per-slot
    budgets travel as validity data, not topology."""
    parents = tree_topology(2, 2)
    n, V = len(parents), 16
    logits = np.full((1, n, V), -10.0, np.float32)
    logits[0, :, 7] = 5.0
    nt = np.zeros((1, n), np.int32)
    nt[0, 1] = 7
    nt[0, 2] = 7
    valid = np.ones((1, n), bool)
    valid[0, 2] = False  # depth-2 node budget-masked out
    out, path, n_emit, _, *_ = _walk(logits, nt, parents, valid)
    # head accepted, then NO valid child at depth 2 → bonus ends the walk
    assert int(n_emit[0]) == 2
    assert np.asarray(path)[0, :2].tolist() == [1, 0]


# ---------------------------------------------------------------------------
# KV commit of the accepted path
# ---------------------------------------------------------------------------


def _tree_cache(lengths, S=2, L=1, ps=4, P=16, maxp=4, kvh=2, d=8,
                quant=False):
    table = np.full((S, maxp), -1, np.int32)
    table[0] = [0, 1, 2, 3]
    table[1] = [4, 5, 6, 7]
    if quant:
        kd = np.zeros((L, P, ps, kvh, d), np.int8)
        sc = np.ones((L, P, ps), np.float32)
        k = QuantPages(jnp.asarray(kd), jnp.asarray(sc))
        v = QuantPages(jnp.asarray(kd.copy()), jnp.asarray(sc.copy()))
    else:
        k = jnp.zeros((L, P, ps, kvh, d), jnp.float32)
        v = jnp.zeros((L, P, ps, kvh, d), jnp.float32)
    return PagedKVCache(k=k, v=v, page_table=jnp.asarray(table),
                        lengths=jnp.asarray(lengths, jnp.int32),
                        page_size=ps), table


def _fill_rows(cache, table, base, n):
    """Stamp rows base..base+n-1 of each slot with slot*100 + node."""
    k = np.array(cache.k)
    v = np.array(cache.v)
    ps = cache.page_size
    for s in range(table.shape[0]):
        for i in range(n):
            pos = base[s] + i
            pg, off = table[s][pos // ps], pos % ps
            k[:, pg, off] = 100 * s + i
            v[:, pg, off] = 100 * s + i + 0.5
    return dataclasses.replace(cache, k=jnp.asarray(k), v=jnp.asarray(v))


def test_commit_tree_path_across_page_boundary():
    # base 5 with page_size 4: node rows 5..8 straddle pages 1 and 2
    cache, table = _tree_cache([5, 5])
    cache = _fill_rows(cache, table, [5, 5], 4)
    # slot 0: chain path (identity — no moves); slot 1: sibling (node 3)
    # backs position base+1, which lives on a DIFFERENT page than node 3
    path = jnp.asarray([[1, 2, 0, 0], [3, 0, 0, 0]], jnp.int32)
    out = commit_tree_path(cache, path, jnp.asarray([True, True]))
    k = np.asarray(out.k)
    ps = cache.page_size
    # slot 0 untouched (path[j] == j+1 everywhere it matters)
    for i in range(4):
        pg, off = table[0][(5 + i) // ps], (5 + i) % ps
        assert k[0, pg, off, 0, 0] == i
    # slot 1: position 6 now holds node 3's row; the root row and the
    # optimistic source row are untouched
    pg, off = table[1][6 // ps], 6 % ps
    assert k[0, pg, off, 0, 0] == 103
    assert np.asarray(out.v)[0, pg, off, 0, 0] == 103.5
    pg, off = table[1][5 // ps], 5 % ps
    assert k[0, pg, off, 0, 0] == 100
    # lengths are the CALLER's business (rollback_to_length), not commit's
    assert np.asarray(out.lengths).tolist() == [5, 5]


def test_commit_tree_path_never_touches_prefix_rows():
    """Rows strictly below lengths + 1 (the committed prompt, possibly
    refcount-shared prefix-cache pages) are never written: every
    destination is lengths + 1 + j with path > 0."""
    cache, table = _tree_cache([5, 3])
    cache = _fill_rows(cache, table, [0, 0], 8)  # stamp the WHOLE prefix
    before = np.asarray(cache.k).copy()
    path = jnp.asarray([[3, 0, 0, 0], [2, 3, 0, 0]], jnp.int32)
    out = commit_tree_path(cache, path, jnp.asarray([True, True]))
    after = np.asarray(out.k)
    ps = cache.page_size
    for s, base in ((0, 5), (1, 3)):
        for pos in range(base + 1):  # prompt rows + the root row
            pg, off = table[s][pos // ps], pos % ps
            np.testing.assert_array_equal(after[0, pg, off],
                                          before[0, pg, off])
    # inactive slots never move rows either
    out2 = commit_tree_path(cache, path, jnp.asarray([False, False]))
    np.testing.assert_array_equal(np.asarray(out2.k), before)


def test_commit_tree_path_quant_moves_bits_verbatim():
    """int8 pools move data + per-row scale verbatim — a dequant/requant
    round trip would recompute the scale and lose bits."""
    cache, table = _tree_cache([5, 5], quant=True)
    kd = np.array(cache.k.data)
    sc = np.array(cache.k.scale)
    ps = cache.page_size
    for s in range(2):
        for i in range(4):
            pos = 5 + i
            pg, off = table[s][pos // ps], pos % ps
            kd[:, pg, off] = (10 * s + i) % 127
            sc[:, pg, off] = 0.25 * (i + 1)
    q = QuantPages(jnp.asarray(kd), jnp.asarray(sc))
    cache = dataclasses.replace(cache, k=q, v=q)
    path = jnp.asarray([[1, 2, 0, 0], [3, 0, 0, 0]], jnp.int32)
    out = commit_tree_path(cache, path, jnp.asarray([True, True]))
    pg, off = table[1][6 // ps], 6 % ps
    assert np.asarray(out.k.data)[0, pg, off, 0, 0] == 13
    assert np.asarray(out.k.scale)[0, pg, off] == 1.0


# ---------------------------------------------------------------------------
# tree-masked attention: chain degeneracy + kernel differential
# ---------------------------------------------------------------------------


def test_verify_ref_tree_chain_degenerates_to_legacy():
    """tree_pos = arange, lower-triangular ancestor mask == the legacy
    chain verify bit-for-bit (same math, the tree branch just spells the
    causal mask explicitly)."""
    rng = np.random.default_rng(3)
    L, P, ps, kvh, d, h = 2, 32, 8, 2, 16, 4
    S, maxp, T = 3, 6, 4
    kp = jnp.asarray(rng.normal(size=(L, P, ps, kvh, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(L, P, ps, kvh, d)), jnp.float32)
    table = jnp.asarray(
        rng.choice(32, size=S * maxp, replace=False).reshape(S, maxp),
        jnp.int32)
    lengths = jnp.asarray([13, 0, 37], jnp.int32)
    q = jnp.asarray(rng.normal(size=(S, T, h, d)), jnp.float32)
    kc = jnp.asarray(rng.normal(size=(S, T, kvh, d)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(S, T, kvh, d)), jnp.float32)
    want = A.paged_attention_verify_ref(
        q, kp, vp, table, lengths, ps, kc, vc, layer=jnp.int32(1))
    chain_pos = np.arange(T, dtype=np.int32)
    chain_mask = np.tril(np.ones((T, T), bool))
    got = A.paged_attention_verify_ref(
        q, kp, vp, table, lengths, ps, kc, vc, layer=jnp.int32(1),
        tree_pos=chain_pos, tree_mask=chain_mask)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=1e-6, atol=1e-6)


def test_ragged_kernel_tree_leg_matches_ref():
    """Interpret-mode ragged kernel with the tree scalar-prefetch rows
    (depths + ancestor bitmasks) matches the tree-masked reference —
    a real branchy topology, not the chain degenerate."""
    rng = np.random.default_rng(4)
    L, P, ps, kvh, d, h = 2, 32, 8, 2, 16, 4
    S, maxp = 3, 6
    parents = tree_topology(2, 3)  # [-1, 0, 1, 0, 0] — N = 5
    T = len(parents)
    depths = tree_depths(parents)
    anc = tree_ancestor_mask(parents)
    bits = tree_ancestor_bits(parents)
    kp = jnp.asarray(rng.normal(size=(L, P, ps, kvh, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(L, P, ps, kvh, d)), jnp.float32)
    table = jnp.asarray(
        rng.choice(32, size=S * maxp, replace=False).reshape(S, maxp),
        jnp.int32)
    lengths = jnp.asarray([13, 0, 37], jnp.int32)
    q = jnp.asarray(rng.normal(size=(S, T, h, d)), jnp.float32)
    kc = jnp.asarray(rng.normal(size=(S, T, kvh, d)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(S, T, kvh, d)), jnp.float32)
    for window in (0, 9):
        want = A.paged_attention_verify_ref(
            q, kp, vp, table, lengths, ps, kc, vc, layer=jnp.int32(0),
            window=window, tree_pos=depths, tree_mask=anc)
        _, got = PK.ragged_attention(
            kp, vp, ps, q_group=q, page_table=table,
            group_lengths=lengths, k_group=kc, v_group=vc,
            layer=jnp.int32(0), interpret=True, window=window,
            tree_pos=jnp.asarray(depths), tree_bits=jnp.asarray(bits))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


def test_ragged_dispatcher_routes_tree_to_ref():
    """The jnp dispatcher path accepts tree args and matches the direct
    reference (the engine's CPU tier-1 route)."""
    rng = np.random.default_rng(5)
    L, P, ps, kvh, d, h = 1, 16, 8, 2, 16, 4
    S, maxp = 2, 4
    parents = tree_topology(2, 2)
    T = len(parents)
    depths, anc = tree_depths(parents), tree_ancestor_mask(parents)
    kp = jnp.asarray(rng.normal(size=(L, P, ps, kvh, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(L, P, ps, kvh, d)), jnp.float32)
    table = jnp.asarray(
        rng.choice(16, size=S * maxp, replace=False).reshape(S, maxp),
        jnp.int32)
    lengths = jnp.asarray([9, 3], jnp.int32)
    q = jnp.asarray(rng.normal(size=(S, T, h, d)), jnp.float32)
    kc = jnp.asarray(rng.normal(size=(S, T, kvh, d)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(S, T, kvh, d)), jnp.float32)
    want = A.paged_attention_verify_ref(
        q, kp, vp, table, lengths, ps, kc, vc, layer=jnp.int32(0),
        tree_pos=depths, tree_mask=anc)
    _, got = A.ragged_paged_attention(
        kp, vp, ps, q_group=q, page_table=table, group_lengths=lengths,
        k_group=kc, v_group=vc, layer=jnp.int32(0), use_pallas=False,
        tree_pos=depths, tree_mask=anc)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# drafter units
# ---------------------------------------------------------------------------


def test_draft_model_drafter_batch_and_slot_isolation(tree_on):
    d = tree_on._drafter
    assert isinstance(d, DraftModelDrafter)
    assert d.kind == "model" and d.tree
    out = d.draft_batch({0: [5, 6, 7], 2: [9, 9, 9, 9]}, 3, 2)
    assert set(out) == {0, 2}
    for chain, alts in out.values():
        assert len(chain) == 3 and len(alts) == 1
        # the first alternative differs from the chain head by contract
        # (top-k rank 1 vs rank 0)
        assert alts[0] != chain[0]
    # overflow slots stop proposing instead of corrupting the pool
    long_ids = list(range(d.max_context))
    assert d.draft_batch({1: long_ids}, 3, 2) == {}
    d.reset_slot(0)
    d.reset_slot(2)
    assert d._ctx[0] == [] and d._ctx[2] == []


def test_unknown_draft_model_falls_back_to_ngram():
    eng = InferenceEngine(EngineConfig(
        **TINY, spec_decode=True, spec_k=2, draft_model="no-such-model"))
    assert eng._spec_k == 2
    assert getattr(eng._drafter, "kind", None) == "ngram"


# ---------------------------------------------------------------------------
# stream parity
# ---------------------------------------------------------------------------


def test_greedy_parity_tree_vs_off_with_real_acceptance(tree_on, spec_off):
    for prompt in (REP_PROMPT, "hello world, here we go"):
        r_off = spec_off.generate(GenerationRequest(
            id="o", prompt=prompt, options=dict(REP_OPTS)))
        r_on = tree_on.generate(GenerationRequest(
            id="t", prompt=prompt, options=dict(REP_OPTS)))
        assert r_on.token_ids == r_off.token_ids, prompt
        assert r_on.text == r_off.text
        assert r_on.spec_proposed > 0
        assert r_on.spec_accepted > 0


def test_greedy_parity_concurrent_tree_batch(tree_on, spec_off):
    opts = {"temperature": 0.0, "repeat_penalty": 1.0, "num_predict": 10}
    prompts = ("aa aa aa aa", "bc bc bc bc", "hello")
    solo = {
        p: spec_off.generate(GenerationRequest(
            id=p, prompt=p, options=dict(opts))).token_ids
        for p in prompts
    }
    results = {}

    def mk(p):
        def cb(d, done, res):
            if done:
                results[p] = res.token_ids
        return cb

    for p in prompts:
        tree_on.submit(GenerationRequest(
            id=p, prompt=p, options=dict(opts), on_chunk=mk(p)))
    while len(results) < len(prompts):
        tree_on.step()
    assert results == solo


def test_greedy_parity_warm_prefix_cache(tree_on, spec_off):
    """A warm replay admits through cached prefix pages — the tree
    verify must keep byte parity on top of the reused KV."""
    opts = {"temperature": 0.0, "repeat_penalty": 1.0, "num_predict": 12}
    prompt = "cache me twice cache me twice"
    want = spec_off.generate(GenerationRequest(
        id="w0", prompt=prompt, options=dict(opts))).token_ids
    cold = tree_on.generate(GenerationRequest(
        id="w1", prompt=prompt, options=dict(opts)))
    warm = tree_on.generate(GenerationRequest(
        id="w2", prompt=prompt, options=dict(opts)))
    assert cold.token_ids == want
    assert warm.token_ids == want
    assert warm.cached_tokens > 0  # the replay really hit the cache


def test_greedy_parity_resume_mid_stream(tree_on, spec_off):
    """Splitting a stream at a watermark (result.context → prompt_ids)
    and resuming must reproduce the unsplit stream, spec-off and
    tree-spec alike."""
    opts = {"temperature": 0.0, "repeat_penalty": 1.0}
    prompt = "resume ab resume ab resume"
    full = spec_off.generate(GenerationRequest(
        id="f", prompt=prompt, options={**opts, "num_predict": 16}))

    def split_run(eng):
        head = eng.generate(GenerationRequest(
            id="h", prompt=prompt, options={**opts, "num_predict": 8}))
        tail = eng.generate(GenerationRequest(
            id="t", prompt_ids=list(head.context),
            options={**opts, "num_predict": 8}))
        return head.token_ids + tail.token_ids

    assert split_run(spec_off) == full.token_ids
    assert split_run(tree_on) == full.token_ids


def test_sampled_seeded_deterministic_tree(tree_on):
    """Sampled tree streams are not byte-equal to spec-off (documented:
    the DISTRIBUTION is preserved) but must stay deterministic per
    (seed, prompt)."""
    opts = {"temperature": 0.9, "seed": 11, "num_predict": 12}
    r1 = tree_on.generate(GenerationRequest(
        id="s1", prompt=REP_PROMPT, options=dict(opts)))
    r2 = tree_on.generate(GenerationRequest(
        id="s2", prompt=REP_PROMPT, options=dict(opts)))
    assert r1.token_ids == r2.token_ids


def test_num_predict_exact_under_tree(tree_on):
    res = tree_on.generate(GenerationRequest(
        id="np", prompt=REP_PROMPT, options={**REP_OPTS, "num_predict": 7}))
    assert res.eval_count == 7
    assert res.done_reason == "length"


# ---------------------------------------------------------------------------
# hygiene
# ---------------------------------------------------------------------------


def test_zero_steady_recompiles_with_tree_armed(tree_on):
    """Varying batch fill, per-slot budgets, and ragged accept depths all
    run through ONE compiled tree-verify program per topology."""
    assert tree_on.perf.armed  # fixtures above completed requests
    before = recompile_totals()["steady"]
    opts = {"temperature": 0.0, "repeat_penalty": 1.0, "num_predict": 6}
    done = []
    for n in (1, 2, 3):
        for i in range(n):
            tree_on.submit(GenerationRequest(
                id=f"fill{n}-{i}", prompt=REP_PROMPT if i % 2 else "hello",
                options=dict(opts),
                on_chunk=lambda d, fin, res: fin and done.append(res)))
        target = sum((1, 2, 3)[: (1, 2, 3).index(n) + 1])
        while len(done) < target:
            tree_on.step()
    assert recompile_totals()["steady"] == before


def test_tree_stats_flow_to_batch_state(tree_on):
    tree_on.generate(GenerationRequest(
        id="st", prompt=REP_PROMPT, options=dict(REP_OPTS)))
    state = tree_on.batch_state()["specDecode"]
    assert state["drafter"] == "model"
    assert state["treeWidth"] == 2
    assert state["steps"] > 0
    assert state["draft_ns"] > 0
    assert state["emitted"] >= state["accepted"]
