"""Shared-state sanitizer units (ISSUE 13): cross-thread unguarded
mutation of registered hot state must be flagged; the same mutation with
a common lock, single-threaded mutation, and unregistered objects must
stay clean.

Like the lockcheck units, these drive the monitor through directly
constructed lock proxies (``make_lock``) — no global factory install, so
they run safely alongside any suite regardless of GRIDLLM_SANITIZE.
"""

import threading

import pytest

from gridllm_tpu.analysis import statecheck
from gridllm_tpu.analysis.lockcheck import make_lock


class Hot:
    def __init__(self):
        self.table = {}
        self.items = []
        self.counter = 0


@pytest.fixture(autouse=True)
def _armed(monkeypatch):
    # snapshot/restore instead of plain reset (the lockcheck pattern):
    # under GRIDLLM_SANITIZE=1 the monitor is process-global and the
    # conftest sessionfinish hook judges it — these tests must not erase
    # records (or a real violation!) accumulated by earlier suites, and
    # their own seeded violations must not leak into the session verdict.
    monkeypatch.setenv("GRIDLLM_SANITIZE", "1")
    saved = statecheck.snapshot()
    statecheck.reset()
    yield
    statecheck.reset()
    statecheck.restore(saved)


def _in_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()


def test_cross_thread_unguarded_dict_write_flagged():
    obj = statecheck.track_object(Hot(), "t1", ("table",))
    obj.table["a"] = 1                       # main thread, no locks
    _in_thread(lambda: obj.table.pop("a"))   # second thread, no locks
    v = statecheck.violations()
    assert any(x["object"] == "t1" and x["attr"] == "table" for x in v), v
    with pytest.raises(statecheck.SharedStateError, match="t1.table"):
        statecheck.assert_clean()


def test_cross_thread_attr_rebind_flagged():
    obj = statecheck.track_object(Hot(), "t2", ("counter",))
    obj.counter = 1
    _in_thread(lambda: setattr(obj, "counter", 2))
    assert any(x["attr"] == "counter" for x in statecheck.violations())


def test_common_lock_keeps_cross_thread_writes_clean():
    lk = make_lock()
    obj = statecheck.track_object(Hot(), "t3", ("table", "items"))

    def guarded_writes():
        with lk:
            obj.table["k"] = 1
            obj.items.append(1)

    guarded_writes()
    _in_thread(guarded_writes)
    assert statecheck.violations() == []
    statecheck.assert_clean()


def test_disjoint_locks_are_not_a_guard():
    # each thread holds A lock — just not the SAME one; the intersection
    # over writes is empty and the race is real. Separate lines: locks
    # are keyed by creation site, same-site twins deliberately collapse
    # (lockcheck's twin exemption).
    lk_a = make_lock()
    lk_b = make_lock()
    obj = statecheck.track_object(Hot(), "t4", ("table",))
    with lk_a:
        obj.table["x"] = 1

    def other():
        with lk_b:
            obj.table["x"] = 2

    _in_thread(other)
    assert any(x["attr"] == "table" for x in statecheck.violations())


def test_single_thread_unlocked_writes_are_clean():
    obj = statecheck.track_object(Hot(), "t5", ("table", "items", "counter"))
    for i in range(10):
        obj.table[i] = i
        obj.items.append(i)
        obj.counter = i
    assert statecheck.violations() == []


def test_rebound_container_stays_tracked():
    obj = statecheck.track_object(Hot(), "t6", ("items",))
    obj.items = [1, 2]          # rebind to a plain list → re-wrapped
    _in_thread(lambda: obj.items.append(3))
    v = statecheck.violations()
    assert any(x["object"] == "t6" and x["attr"] == "items" for x in v), v


def test_untracked_attrs_and_objects_ignored():
    obj = statecheck.track_object(Hot(), "t7", ("table",))
    other = Hot()  # same (patched) class, never registered
    obj.counter = 1
    _in_thread(lambda: setattr(obj, "counter", 2))
    other.table["x"] = 1
    _in_thread(lambda: other.table.pop("x"))
    assert statecheck.violations() == []


def test_disabled_is_a_noop(monkeypatch):
    monkeypatch.setenv("GRIDLLM_SANITIZE", "0")
    obj = Hot()
    assert statecheck.track_object(obj, "t8", ("table",)) is obj
    assert type(obj.table) is dict  # not wrapped
    obj.table["a"] = 1
    _in_thread(lambda: obj.table.pop("a"))
    assert statecheck.violations() == []


def test_report_shape():
    obj = statecheck.track_object(Hot(), "t9", ("table",))
    rep = statecheck.report()
    assert rep["ok"] and rep["violations"] == []
    assert rep["tracked_objects"] >= 1
    ref = obj  # keep the object alive through the report  # noqa: F841


def test_dead_object_registration_is_reaped():
    statecheck.track_object(Hot(), "t10", ("table",))  # dropped at once
    import gc

    gc.collect()
    assert statecheck.report()["tracked_objects"] == 0
