"""Int8 weight-only quantization (VERDICT r03 next-round #6): numeric
parity on a tiny config + the memory-math assertion that llama3:70b fits
a v5e-8 slice (BASELINE config #3 — arithmetically impossible at bf16)."""

import jax
import jax.numpy as jnp
import numpy as np

from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine
from gridllm_tpu.models import llama
from gridllm_tpu.models.configs import get_config
from gridllm_tpu.ops.quant import (
    QuantizedTensor,
    params_nbytes,
    qdot,
    quantize_array,
    quantize_np_leaf,
    quantize_params,
)

TINY = dict(
    model="tiny-llama", max_slots=2, page_size=8, num_pages=32,
    max_pages_per_slot=8, prefill_buckets=(16, 32),
)


def test_qdot_matches_dense_within_tolerance():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 32), jnp.float32) * 0.1
    want = x @ w
    got = qdot(x, quantize_array(w))
    # per-out-channel int8: relative error ~1/254 of the channel amax
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0.05, atol=0.02)


def test_quantize_np_leaf_matches_device_quant():
    w = np.random.RandomState(0).randn(3, 16, 8).astype(np.float32) * 0.2
    a = quantize_array(jnp.asarray(w))
    b = quantize_np_leaf("wq", w)
    np.testing.assert_array_equal(np.asarray(a.q), b.q)
    np.testing.assert_allclose(np.asarray(a.scale), b.scale, rtol=1e-6)
    # non-matmul names pass through untouched
    assert quantize_np_leaf("attn_norm", w) is w


def test_forward_logits_parity_int8_vs_dense():
    """Tiny-llama full forward: int8 weights track the fp32 logits to a
    loose tolerance (quantization noise only — same argmax on most
    positions is NOT asserted; goldens protect exactness of the dense
    path, this protects the int8 plumbing)."""
    cfg = get_config("tiny-llama")
    params = llama.init_params(cfg, jax.random.PRNGKey(2), jnp.float32)
    toks = jnp.asarray([[5, 17, 99, 3, 42, 7]], jnp.int32)
    dense = np.asarray(llama.forward(params, cfg, toks))
    qparams = quantize_params(params)
    assert isinstance(qparams["layers"]["wq"], QuantizedTensor)
    quant = np.asarray(llama.forward(qparams, cfg, toks))
    # compare top-1 agreement + bounded error on the logit scale
    err = np.abs(dense - quant).max() / (np.abs(dense).max() + 1e-6)
    assert err < 0.15, f"relative logit error {err:.3f}"


def test_engine_serves_int8():
    eng = InferenceEngine(EngineConfig(**TINY, quantize="int8"))
    res = eng.generate(GenerationRequest(
        id="q", prompt="hello", options={"temperature": 0, "num_predict": 6}))
    assert res.eval_count == 6
    assert res.done_reason == "length"


def test_70b_int8_fits_v5e8_memory_math():
    """The BASELINE #3 budget: llama3:70b int8 params + a real KV pool
    must fit 8×16 GB. At bf16 the params alone (~140 GB) exceed the slice;
    int8 must land the total under budget with ≥20% headroom for
    activations/runtime."""
    cfg = get_config("llama3:70b")
    proto = jax.eval_shape(
        lambda: quantize_params(
            llama.init_params(cfg, jax.random.PRNGKey(0), jnp.bfloat16)
        )
    )
    pbytes = params_nbytes(proto)
    dense = jax.eval_shape(
        lambda: llama.init_params(cfg, jax.random.PRNGKey(0), jnp.bfloat16)
    )
    assert params_nbytes(dense) > 128 * 2**30  # bf16 provably does NOT fit
    # KV pool: 1024 pages × 64 tokens ≈ 65k cached tokens, bf16 (~20 GiB)
    kv = (2 * cfg.num_layers * 1024 * 64 * cfg.num_kv_heads
          * cfg.head_dim_ * 2)
    budget = 8 * 16 * 2**30
    assert pbytes + kv < budget * 0.8, (
        f"params {pbytes/2**30:.1f} GiB + kv {kv/2**30:.1f} GiB "
        f"vs budget {budget/2**30:.0f} GiB"
    )


def test_quantized_param_shardings_resolve():
    """parallel.param_shardings must produce a congruent sharding tree for
    quantized pytrees (q inherits the weight's spec; scale replicates)."""
    from jax.sharding import Mesh
    from gridllm_tpu.parallel.sharding import param_shardings

    cfg = get_config("tiny-llama")
    devs = np.array(jax.devices()[:8]).reshape(1, 8, 1, 1, 1)
    mesh = Mesh(devs, ("dp", "tp", "sp", "ep", "pp"))
    proto = jax.eval_shape(
        lambda: quantize_params(
            llama.init_params(cfg, jax.random.PRNGKey(0), jnp.bfloat16)
        )
    )
    sh = param_shardings(proto, mesh)
    # congruent tree: every leaf has a sharding
    jax.tree_util.tree_map(lambda p, s: None, proto, sh)


def test_llava_vision_subtrees_never_quantize():
    """VERDICT r04 review: the vision tower's wq/wk/wv/wo NAMES collide
    with QUANT_LEAVES but are consumed with plain `@` — int8 must skip the
    vision/projector subtrees (and the engine must serve images under
    quantize="int8")."""
    import jax

    from gridllm_tpu.models import llava
    from gridllm_tpu.models.configs import get_config
    from gridllm_tpu.ops.quant import QuantizedTensor, quantize_params

    cfg = get_config("tiny-llava")
    params = llava.init_params(cfg, jax.random.PRNGKey(0))
    q = quantize_params(params)
    assert isinstance(q["layers"]["wq"], QuantizedTensor)  # LM still quantizes
    flat = jax.tree_util.tree_leaves_with_path(
        {"vision": q["vision"], "projector": q["projector"]},
        is_leaf=lambda x: isinstance(x, QuantizedTensor),
    )
    assert flat and not any(
        isinstance(leaf, QuantizedTensor) for _, leaf in flat
    )


def test_llava_engine_serves_int8():
    import base64
    import io

    import numpy as np

    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.engine.engine import GenerationRequest
    from PIL import Image

    rng = np.random.default_rng(7)
    img = Image.fromarray(rng.integers(0, 255, (20, 20, 3), np.uint8))
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    b64 = base64.b64encode(buf.getvalue()).decode()

    eng = InferenceEngine(EngineConfig(
        model="tiny-llava", quantize="int8", max_slots=1, page_size=16,
        num_pages=32, max_pages_per_slot=8, prefill_buckets=(32,),
    ))
    res = eng.generate(GenerationRequest(
        id="q1", prompt="hi", images=[b64],
        options={"temperature": 0, "num_predict": 3, "seed": 0},
    ))
    assert res.done_reason in ("stop", "length")
    assert res.eval_count >= 1
