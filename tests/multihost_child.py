"""Child process for the multi-host worker-group test (test_multihost.py).

Each process: joins the jax group (CPU, 4 local devices → 8 global), proves
a cross-host collective works, then runs GroupMembership over the RESP
broker. Process 0 (liaison) registers ONE logical worker and, on slice
failure, announces `worker:disconnected` (the scheduler's orphan trigger).

Usage: python multihost_child.py <proc_id> <coord_port> <broker_port> <worker_id>
"""

import asyncio
import os
import sys


def main() -> None:
    pid, coord_port, broker_port, worker_id = (
        int(sys.argv[1]), sys.argv[2], sys.argv[3], sys.argv[4]
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["GRIDLLM_COORD_ADDR"] = f"127.0.0.1:{coord_port}"
    os.environ["GRIDLLM_NUM_PROCS"] = "2"
    os.environ["GRIDLLM_PROC_ID"] = str(pid)

    import jax

    jax.config.update("jax_platforms", "cpu")

    from gridllm_tpu.parallel.distributed import GroupConfig, initialize_group

    group = initialize_group(GroupConfig.from_env())
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4

    # one real cross-host collective over the slice mesh
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    from gridllm_tpu.parallel.mesh import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(tp=8))
    total = jax.jit(shard_map(
        lambda x: jax.lax.psum(x, "tp"), mesh=mesh,
        in_specs=P("tp"), out_specs=P(),
    ))(jnp.arange(8.0))
    assert float(total[0]) == 28.0, total
    print(f"[{pid}] collective ok", flush=True)

    asyncio.run(run_group(group, broker_port, worker_id))


async def run_group(group, broker_port: str, worker_id: str) -> None:
    import json

    from gridllm_tpu.bus import create_bus
    from gridllm_tpu.utils.types import ModelInfo, NodeCapabilities, WorkerInfo
    from gridllm_tpu.worker.group import GroupMembership, fail_logical_worker

    bus = create_bus(f"resp://127.0.0.1:{broker_port}", key_prefix="T:")
    await bus.connect()
    stop = asyncio.Event()

    async def on_failure(reason: str) -> None:
        if group.is_liaison:
            await fail_logical_worker(bus, worker_id, reason)
            print(f"[{group.process_id}] logical worker failed: {reason}",
                  flush=True)
        stop.set()

    membership = GroupMembership(
        bus, worker_id, group, heartbeat_interval_s=0.2,
        on_slice_failure=on_failure,
    )
    await membership.start()

    if group.is_liaison:
        info = WorkerInfo(
            workerId=worker_id,
            capabilities=NodeCapabilities(
                workerId=worker_id,
                availableModels=[ModelInfo(name="m1")],
            ),
            status="online",
        )
        await bus.hset("workers", worker_id, info.model_dump_json())
        await bus.publish("worker:registered", info.model_dump_json())

    print(f"[{group.process_id}] group ready", flush=True)
    if group.is_liaison:
        # liaison lives until the slice breaks (parent kills the follower)
        await asyncio.wait_for(stop.wait(), timeout=30)
    else:
        # follower: hold membership until the parent kills this process
        await asyncio.sleep(30)
    await membership.stop()
    await bus.disconnect()
    # fail-fast exit: jax.distributed's atexit teardown can block forever
    # once a slice member is SIGKILLed (coordinator waits on dead agents) —
    # same reason worker/main.py force-exits on slice failure
    os._exit(0)


if __name__ == "__main__":
    main()
