"""Child process for the multi-host SERVING test (test_multihost.py).

Runs the REAL worker entrypoint (gridllm_tpu.worker.main.run) as one
member of a 2-process slice over 2×4 virtual CPU devices: process 0 is
the liaison (bus worker + engines + plan publisher), process 1 the
follower (same engines, replaying the liaison's step plan). The parent
drives a real /ollama/api/generate through gateway + scheduler against
the shared broker — the request's tokens are computed by jit programs
sharded across BOTH processes.

Usage: python multihost_serve_child.py <proc_id> <coord_port> <broker_port>
         <worker_id> <worker_http_port>
"""

import asyncio
import os
import sys


def main() -> None:
    pid, coord_port, broker_port, worker_id, wport = sys.argv[1:6]
    os.environ.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "GRIDLLM_COORD_ADDR": f"127.0.0.1:{coord_port}",
        "GRIDLLM_NUM_PROCS": "2",
        "GRIDLLM_PROC_ID": pid,
        "WORKER_ID": worker_id,
        "WORKER_PORT": wport,
        "GRIDLLM_BUS_URL": f"resp://127.0.0.1:{broker_port}",
        "GRIDLLM_MODELS": "tiny-llama",
        "GRIDLLM_MESH_SHAPE": "tp:8",   # wq/wo shard over both processes
        "GRIDLLM_DTYPE": "float32",
        "GRIDLLM_PREFILL_BUCKETS": "32,64",
        "HEARTBEAT_INTERVAL": "500",  # worker config reads HEARTBEAT_INTERVAL
    })
    import jax

    jax.config.update("jax_platforms", "cpu")

    from gridllm_tpu.worker.main import run

    print(f"[{pid}] starting worker", flush=True)
    try:
        asyncio.run(run())
    finally:
        # fail-fast exit: jax.distributed atexit teardown can hang once a
        # peer is gone (same reason worker/main.py force-exits on failure)
        os._exit(0)


if __name__ == "__main__":
    main()
