"""Ollama option semantics at the worker: template/system/suffix rendering,
format:"json" extraction, think splitting, tool-call parsing — VERDICT r03
missing #2/#3. Reference behavior contract:
client/src/services/OllamaService.ts:197-226 (options forwarded and
applied), server/src/routes/ollama.ts:26-56 (option schema)."""

import asyncio
import json

import pytest

from gridllm_tpu.engine.tokenizer import get_tokenizer
from gridllm_tpu.worker.prompting import (
    build_generate_prompt,
    extract_json,
    json_instruction,
    parse_tool_calls,
    render_chat_full,
    render_template,
    split_thinking,
)

TOK = get_tokenizer(None, 512)  # byte tokenizer (no chat template)


# ---------------------------------------------------------------------------
# Go-template subset
# ---------------------------------------------------------------------------

def test_render_template_vars_and_ifs():
    t = "{{ if .System }}SYS:{{ .System }}\n{{ end }}USER:{{ .Prompt }}"
    assert render_template(t, {"System": "be brief", "Prompt": "hi"}) == (
        "SYS:be brief\nUSER:hi"
    )
    assert render_template(t, {"System": "", "Prompt": "hi"}) == "USER:hi"


def test_render_template_suffix_fim():
    t = "<PRE>{{ .Prompt }}<SUF>{{ .Suffix }}<MID>"
    out = render_template(t, {"Prompt": "def f(", "Suffix": "return x"})
    assert out == "<PRE>def f(<SUF>return x<MID>"


def test_generate_prompt_paths():
    # raw bypasses everything
    assert build_generate_prompt(
        "p", TOK, system="s", template="T{{ .Prompt }}", raw=True
    ) == "p"
    # custom template wins
    assert build_generate_prompt(
        "p", TOK, system="s", template="[{{ .System }}]{{ .Prompt }}"
    ) == "[s]p"
    # system without template → framed (byte tokenizer has no chat template)
    out = build_generate_prompt("p", TOK, system="be nice")
    assert "be nice" in out and out.index("be nice") < out.index("p")
    # suffix without a template referencing it is ignored (Ollama semantics)
    assert build_generate_prompt("p", TOK, suffix="tail") == "p"


# ---------------------------------------------------------------------------
# format: json
# ---------------------------------------------------------------------------

def test_extract_json_trims_prose():
    assert extract_json('Sure! {"a": [1, 2]} hope that helps') == '{"a": [1, 2]}'
    assert extract_json("no json here") == "no json here"
    assert json.loads(extract_json('x ["ok", {"k": "v"}] y')) == ["ok", {"k": "v"}]


def test_json_instruction_includes_schema():
    schema = {"type": "object", "properties": {"a": {"type": "number"}}}
    assert "JSON schema" in json_instruction(schema)
    assert '"properties"' in json_instruction(schema)
    assert "JSON" in json_instruction("json")


# ---------------------------------------------------------------------------
# thinking
# ---------------------------------------------------------------------------

def test_split_thinking():
    th, rest = split_thinking("<think>hmm\nplan</think>The answer is 4.")
    assert th == "hmm\nplan"
    assert rest == "The answer is 4."
    th, rest = split_thinking("plain")
    assert th is None and rest == "plain"


# ---------------------------------------------------------------------------
# tool calls
# ---------------------------------------------------------------------------

def test_parse_tool_calls_hermes_tag():
    text = ('<tool_call>{"name": "get_weather", "arguments": '
            '{"city": "Paris"}}</tool_call>')
    calls, rest = parse_tool_calls(text)
    assert calls == [{"function": {"name": "get_weather",
                                   "arguments": {"city": "Paris"}}}]
    assert rest == ""


def test_parse_tool_calls_llama3_bare_json():
    text = '{"name": "add", "parameters": {"a": 1, "b": 2}}'
    calls, rest = parse_tool_calls(text)
    assert calls == [{"function": {"name": "add",
                                   "arguments": {"a": 1, "b": 2}}}]
    assert rest == ""


def test_parse_tool_calls_plain_text_untouched():
    calls, rest = parse_tool_calls("The answer is 42.")
    assert calls == [] and rest == "The answer is 42."
    # a JSON object that is NOT a tool call stays content
    calls, rest = parse_tool_calls('{"answer": 42}')
    assert calls == [] and rest == '{"answer": 42}'


def test_render_chat_tools_in_prompt():
    tools = [{"type": "function", "function": {
        "name": "get_weather",
        "parameters": {"type": "object", "properties": {}}}}]
    out = render_chat_full(
        [{"role": "user", "content": "weather?"}], TOK, tools=tools
    )
    assert "get_weather" in out and "weather?" in out


def test_render_chat_openai_string_arguments_normalized():
    msgs = [
        {"role": "user", "content": "add 1 2"},
        {"role": "assistant", "content": "", "tool_calls": [
            {"type": "function", "function": {
                "name": "add", "arguments": '{"a": 1, "b": 2}'}}]},
        {"role": "tool", "content": "3"},
    ]
    out = render_chat_full(msgs, TOK)
    assert '"a": 1' in out and "[tool result] 3" in out


# ---------------------------------------------------------------------------
# end-to-end through the worker (in-memory bus, tiny engine)
# ---------------------------------------------------------------------------

@pytest.fixture()
def stack():
    from gridllm_tpu.bus.memory import InMemoryBus
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.utils.config import WorkerConfig
    from gridllm_tpu.worker.service import WorkerService

    async def build():
        eng = InferenceEngine(EngineConfig(
            model="tiny-llama", max_slots=2, page_size=8, num_pages=32,
            max_pages_per_slot=8, prefill_buckets=(16, 32),
        ))
        bus = InMemoryBus()
        await bus.connect()
        worker = WorkerService(bus, {"tiny-llama": eng}, WorkerConfig())
        await worker.start()
        return bus, worker

    return build


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_worker_applies_system_and_format(stack):
    """system travels into the rendered prompt; format triggers JSON
    extraction on the final text (soft-constraint + hard-extract)."""
    from gridllm_tpu.utils.types import InferenceRequest, JobAssignment

    async def main():
        bus, worker = await stack()
        results = {}

        async def on_done(_ch, raw):
            d = json.loads(raw)
            results[d["jobId"]] = d

        await bus.subscribe("job:completed", on_done)
        req = InferenceRequest(
            id="j1", model="tiny-llama", prompt="hello",
            options={"temperature": 0, "num_predict": 4}, stream=False,
            metadata={"requestType": "inference", "system": "You are terse.",
                      "format": "json"},
        )
        import time as _t
        await worker._execute(JobAssignment(
            jobId="j1", workerId=worker.worker_id, request=req,
            assignedAt=_t.time()))
        await asyncio.sleep(0.05)
        assert "j1" in results and results["j1"]["success"]
        await worker.stop()
        await bus.disconnect()

    _run(main())
