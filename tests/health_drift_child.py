"""Child process for tests/test_health.py: a REAL worker (tiny-llama
engine + WorkerService) whose sampler is silently perturbed — same
engine config (so the same engineConfigHash golden key as a healthy
peer), same latency, same advertised capabilities, wrong bytes.  Models
the silent correctness rot ISSUE 19 targets (corrupted weights, dtype
rot, a bad kernel fallback) that no liveness tier or latency baseline
can see: only the canary's golden output hash catches it.

Usage: python health_drift_child.py <broker_port> <worker_id>
"""

import asyncio
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


async def main() -> None:
    broker_port, worker_id = sys.argv[1], sys.argv[2]
    import jax.numpy as jnp
    from gridllm_tpu.bus import create_bus
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.engine import engine as engine_mod
    from gridllm_tpu.utils.config import WorkerConfig
    from gridllm_tpu.worker.service import WorkerService

    real_sample = engine_mod.sample_tokens

    def rotted_sample(logits, params, token_counts=None):
        # every distribution shifted one vocab slot: greedy argmax lands
        # on a neighbouring token id with identical shapes and timing —
        # the patch must precede engine construction so the jit traces
        # capture it
        return real_sample(jnp.roll(logits, 1, axis=-1), params,
                           token_counts)

    engine_mod.sample_tokens = rotted_sample

    eng = InferenceEngine(EngineConfig(
        model="tiny-llama", max_slots=2, page_size=8, num_pages=32,
        max_pages_per_slot=4, prefill_buckets=(16, 32),
    ))
    bus = create_bus(f"resp://127.0.0.1:{broker_port}")
    await bus.connect()
    svc = WorkerService(
        bus, {"tiny-llama": eng},
        WorkerConfig(worker_id=worker_id, heartbeat_interval_ms=150,
                     resource_monitor_interval_ms=500),
        stream_flush_ms=5,
    )
    await svc.start()
    print("CHILD_READY", flush=True)
    await asyncio.Event().wait()  # run until killed


asyncio.run(main())
