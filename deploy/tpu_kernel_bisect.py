"""Run each Pallas kernel standalone to bisect TPU hangs/crashes.

Why this exists (round-4 war story): interpret-mode tests complete DMA
copies synchronously, so a class of semaphore/DMA bugs only manifests on
real hardware — and a crashed kernel can wedge the axon TPU tunnel for
hours (every later backend init hangs). First hardware contact must
therefore be one kernel per throwaway process, with a health probe
between, so a single bad kernel is identified by name and cannot take
the whole round down. Orchestrated by deploy/tpu_kernel_bisect.sh.

Usage: python deploy/tpu_kernel_bisect.py [flash|streamed|decode|
       decode64|wdecode|wchunk|chunkatt|all]

Shapes mirror the headline bench (3B-class: H=24, KVH=8, D=128) plus the
d=64 qwen2.5-class variant.
"""
import sys
import time

import jax
import jax.numpy as jnp


def log(msg):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


log(f"devices: {jax.devices()}")

from gridllm_tpu.ops import pallas_kernels as pk  # noqa: E402

which = sys.argv[1] if len(sys.argv) > 1 else "all"

# 3B-ish shapes: H=24, KVH=8, D=128, T=1024
B, T, H, KVH, D = 1, 1024, 24, 8, 128
S, PS, NP, MPS = 8, 64, 384, 48
L = 28

key = jax.random.PRNGKey(0)


def _qkv(d):
    q = jax.random.normal(key, (B, T, H, d), jnp.bfloat16)
    k = jax.random.normal(key, (B, T, KVH, d), jnp.bfloat16)
    v = jax.random.normal(key, (B, T, KVH, d), jnp.bfloat16)
    return q, k, v


if which in ("all", "flash"):
    log("flash_prefill...")
    q, k, v = _qkv(D)
    out = pk.flash_prefill(q, k, v, jnp.array([600], jnp.int32))
    jax.block_until_ready(out)
    log(f"flash_prefill OK {out.shape} {float(jnp.abs(out).mean()):.4f}")

if which in ("all", "streamed"):
    log("flash_prefill_streamed...")
    q, k, v = _qkv(D)
    out = pk.flash_prefill_streamed(q, k, v, jnp.array([600], jnp.int32))
    jax.block_until_ready(out)
    log(f"flash_prefill_streamed OK {out.shape} "
        f"{float(jnp.abs(out).mean()):.4f}")

if which in ("all", "decode"):
    log("paged_decode (full-stack pool + layer + k_cur)...")
    kp = jax.random.normal(key, (L, NP, PS, KVH, D), jnp.bfloat16)
    vp = jax.random.normal(key, (L, NP, PS, KVH, D), jnp.bfloat16)
    pt = jnp.tile(jnp.arange(MPS, dtype=jnp.int32)[None], (S, 1))
    lens = jnp.full((S,), 600, jnp.int32)
    q = jax.random.normal(key, (S, H, D), jnp.bfloat16)
    kc = jax.random.normal(key, (S, KVH, D), jnp.bfloat16)
    vc = jax.random.normal(key, (S, KVH, D), jnp.bfloat16)
    out = pk.paged_decode(q, kp, vp, pt, lens, PS, k_cur=kc, v_cur=vc,
                          layer=jnp.int32(3))
    jax.block_until_ready(out)
    # the round-4 wedge case: an INACTIVE slot (len 0) must not corrupt
    # the DMA handshake (pallas_kernels.py merge_cur n_eff guard)
    lens0 = lens.at[3].set(0)
    out = pk.paged_decode(q, kp, vp, pt, lens0, PS, k_cur=kc, v_cur=vc,
                          layer=jnp.int32(3))
    jax.block_until_ready(out)
    log(f"paged_decode OK {out.shape} {float(jnp.abs(out).mean()):.4f}")

if which in ("all", "decode64"):
    # the d=64 serving path: pool allocated lane-padded to 128 (engine
    # _pool_head_dim), q/k_cur/v_cur padded + out sliced by the dispatch
    log("paged decode d=64 via lane-padded pool (qwen2.5-class)...")
    from gridllm_tpu.ops.attention import paged_attention_decode

    d64, dpool = 64, 128
    kp = jax.random.normal(key, (L, NP, PS, KVH, dpool), jnp.bfloat16)
    vp = jax.random.normal(key, (L, NP, PS, KVH, dpool), jnp.bfloat16)
    pt = jnp.tile(jnp.arange(MPS, dtype=jnp.int32)[None], (S, 1))
    lens = jnp.full((S,), 600, jnp.int32)
    q = jax.random.normal(key, (S, H, d64), jnp.bfloat16)
    kc = jax.random.normal(key, (S, KVH, d64), jnp.bfloat16)
    vc = jax.random.normal(key, (S, KVH, d64), jnp.bfloat16)
    out = paged_attention_decode(q, kp, vp, pt, lens, PS, k_cur=kc,
                                 v_cur=vc, layer=jnp.int32(3),
                                 use_pallas=True)
    jax.block_until_ready(out)
    assert out.shape[-1] == d64
    log(f"paged decode d=64 OK {out.shape} {float(jnp.abs(out).mean()):.4f}")

if which in ("all", "chunkatt"):
    log("prefix_chunk (chunked-prefill attention vs paged prefix)...")
    C = 1024
    kp = jax.random.normal(key, (L, NP, PS, KVH, D), jnp.bfloat16)
    vp = jax.random.normal(key, (L, NP, PS, KVH, D), jnp.bfloat16)
    row = jnp.arange(MPS, dtype=jnp.int32)
    q = jax.random.normal(key, (1, C, H, D), jnp.bfloat16)
    kc = jax.random.normal(key, (C, KVH, D), jnp.bfloat16)
    vc = jax.random.normal(key, (C, KVH, D), jnp.bfloat16)
    out = pk.prefix_chunk(q, kp, vp, row, jnp.int32(1024),
                          jnp.int32(1024 + 900), PS, k_cur=kc, v_cur=vc,
                          layer=jnp.int32(3))
    jax.block_until_ready(out)
    log(f"prefix_chunk OK {out.shape} {float(jnp.abs(out).mean()):.4f}")

if which in ("all", "wdecode"):
    log("paged_write_decode...")
    kp = jnp.zeros((L, NP, PS, KVH, D), jnp.bfloat16)
    vp = jnp.zeros((L, NP, PS, KVH, D), jnp.bfloat16)
    lens = jnp.full((S,), 600, jnp.int32)
    kn = jax.random.normal(key, (L, S, KVH, D), jnp.bfloat16)
    vn = jax.random.normal(key, (L, S, KVH, D), jnp.bfloat16)
    page_idx = jnp.arange(S, dtype=jnp.int32)
    o1, o2 = pk.paged_write_decode(kp, vp, kn, vn, page_idx, lens % PS)
    jax.block_until_ready((o1, o2))
    log(f"paged_write_decode OK {o1.shape} {float(jnp.abs(o1).mean()):.6f}")

if which in ("all", "wchunk"):
    log("paged_write_chunk...")
    kp = jnp.zeros((L, NP, PS, KVH, D), jnp.bfloat16)
    vp = jnp.zeros((L, NP, PS, KVH, D), jnp.bfloat16)
    row = jnp.arange(MPS, dtype=jnp.int32)
    kn = jax.random.normal(key, (L, T, KVH, D), jnp.bfloat16)
    vn = jax.random.normal(key, (L, T, KVH, D), jnp.bfloat16)
    o1, o2 = pk.paged_write_chunk(kp, vp, kn, vn, row, jnp.int32(0),
                                  jnp.int32(600), PS)
    jax.block_until_ready((o1, o2))
    log(f"paged_write_chunk OK {o1.shape} {float(jnp.abs(o1).mean()):.6f}")

log("ALL DONE")
