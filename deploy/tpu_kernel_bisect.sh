#!/bin/bash
# First-hardware-contact harness for the Pallas kernels.
#
# A device-side kernel crash (bad DMA/semaphore state) can wedge a
# remote-TPU tunnel so badly that every later backend init hangs —
# round 4 lost its whole benchmarking window to exactly that. So the
# first thing to touch real hardware each round is THIS script, never
# the full bench:
#   1. cheap health probe (matmul) — is the device reachable at all?
#   2. each Pallas kernel in its own throwaway subprocess (bounded by
#      `timeout`), with a fresh health probe after each — a kernel that
#      crashes or wedges is identified BY NAME and the script stops
#      before the next one compounds the damage;
#   3. only if every kernel passes: optionally run the bench
#      (--then-bench), the expensive step that is now safe to attempt.
#
# Usage: deploy/tpu_kernel_bisect.sh [--then-bench] [logdir]
# Exit codes: 0 all kernels healthy; 2 device unreachable; 3 a kernel
# failed or wedged the tunnel (see $logdir/bisect_<kernel>.log).
set -u
cd "$(dirname "$0")/.."

THEN_BENCH=0
[[ "${1:-}" == "--then-bench" ]] && { THEN_BENCH=1; shift; }
LOGDIR="${1:-/tmp/tpu_bisect}"
mkdir -p "$LOGDIR"

PY=${PYTHON:-python}
PROBE_TIMEOUT=${PROBE_TIMEOUT:-120}
KERNEL_TIMEOUT=${KERNEL_TIMEOUT:-420}

say() { echo "[$(date -u +%H:%M:%S)] $*" | tee -a "$LOGDIR/bisect.log"; }

probe() {
  timeout "$PROBE_TIMEOUT" "$PY" -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
print('HEALTH-OK', float((x @ x).sum()), jax.devices())
" 2>&1 | tail -1
}

h=$(probe)
say "initial probe: $h"
if [[ "$h" != HEALTH-OK* ]]; then
  say "device unreachable — not attempting kernels"
  exit 2
fi

for k in flash streamed wdecode wchunk decode decode64 chunkatt; do
  say "kernel $k ..."
  timeout "$KERNEL_TIMEOUT" "$PY" deploy/tpu_kernel_bisect.py "$k" \
    > "$LOGDIR/bisect_$k.log" 2>&1
  rc=$?
  say "kernel $k rc=$rc ($(tail -1 "$LOGDIR/bisect_$k.log" | head -c 120))"
  h=$(probe)
  say "post-$k health: $h"
  if [[ $rc -ne 0 || "$h" != HEALTH-OK* ]]; then
    say "kernel $k FAILED or wedged the tunnel — stopping bisect"
    exit 3
  fi
done
say "all kernels healthy"

if [[ $THEN_BENCH -eq 1 ]]; then
  say "running bench ..."
  timeout 2400 "$PY" bench.py > "$LOGDIR/bench.json" 2> "$LOGDIR/bench.err"
  say "bench rc=$? -> $LOGDIR/bench.json"
  tail -1 "$LOGDIR/bench.json"
fi
