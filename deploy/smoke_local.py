#!/usr/bin/env python
"""Process-level deployment smoke test (VERDICT.md #10).

Boots the SAME service topology as deploy/docker-compose.yml — broker
(gridllm-bus), server (gridllm-server), worker (gridllm-worker) — as three
real OS processes wired over the RESP bus, waits for health, then runs the
differential API-shape gate (tests/integration/differential.py) against
the live stack. This is the compose bundle's service graph executed
without a container runtime (none exists in the build environment; the
Dockerfiles' ENTRYPOINTs invoke exactly these modules).

Usage: python deploy/smoke_local.py   (exit 0 = stack healthy + shapes pass)
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_http(url: str, timeout_s: float, proc: subprocess.Popen, name: str):
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        if proc.poll() is not None:
            raise SystemExit(f"{name} died (rc={proc.returncode})")
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                if r.status == 200:
                    return
        except Exception:
            time.sleep(0.5)
    raise SystemExit(f"{name} not healthy after {timeout_s}s ({url})")


def main() -> int:
    broker_port = free_port()
    server_port = free_port()
    worker_port = free_port()
    env = {
        **os.environ,
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",            # worker engine on CPU (smoke)
        "GRIDLLM_BUS_URL": f"resp://127.0.0.1:{broker_port}",
        "GRIDLLM_MODELS": "tiny-llama",
        "GRIDLLM_PREFILL_BUCKETS": "16,64",
        "PORT": str(server_port),
        "WORKER_PORT": str(worker_port),
        "WORKER_ID": "smoke-worker",
        "LOG_LEVEL": "warning",
    }
    procs: list[tuple[str, subprocess.Popen]] = []

    def spawn(name: str, *argv: str) -> subprocess.Popen:
        p = subprocess.Popen(
            [sys.executable, *argv], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        procs.append((name, p))
        return p

    try:
        spawn("broker", "-m", "gridllm_tpu.bus.broker",
              "--host", "127.0.0.1", "--port", str(broker_port))
        time.sleep(0.5)
        server = spawn("server", "-m", "gridllm_tpu.gateway.main")
        worker = spawn("worker", "-m", "gridllm_tpu.worker.main")

        wait_http(f"http://127.0.0.1:{server_port}/health", 60, server, "server")
        wait_http(f"http://127.0.0.1:{worker_port}/health", 120, worker, "worker")
        print("all services healthy", flush=True)

        # worker registered and the model visible through the API
        t0 = time.time()
        while time.time() - t0 < 60:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server_port}/ollama/api/tags", timeout=5
            ) as r:
                tags = json.load(r)
            if any(m["name"] == "tiny-llama" for m in tags.get("models", [])):
                break
            time.sleep(0.5)
        else:
            raise SystemExit(f"model never appeared in /api/tags: {tags}")
        print("worker registered; model visible in /api/tags", flush=True)

        # one real generation through the whole stack (engine compile incl.)
        req = urllib.request.Request(
            f"http://127.0.0.1:{server_port}/ollama/api/generate",
            data=json.dumps({
                "model": "tiny-llama", "prompt": "smoke", "stream": False,
                "options": {"num_predict": 4, "temperature": 0},
            }).encode(), headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=180) as r:
            body = json.load(r)
        assert body.get("done") and body.get("eval_count") == 4, body
        print(f"generate OK: eval_count={body['eval_count']} "
              f"eval_duration={body['eval_duration']}ns", flush=True)

        # differential shape gate against the live stack
        rc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tests/integration/differential.py"),
             "--endpoint", f"http://127.0.0.1:{server_port}",
             "--model", "tiny-llama"],
            env=env,
        ).returncode
        if rc != 0:
            raise SystemExit(f"differential shape gate failed (rc={rc})")
        print("differential shape gate: PASS", flush=True)
        return 0
    finally:
        for name, p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 10
        for name, p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()


if __name__ == "__main__":
    sys.exit(main())
