#!/usr/bin/env bash
# Bootstrap a Cloud TPU VM as a GridLLM-TPU worker.
#
# Usage (on each TPU VM host):
#   REDIS_HOST=<bus-node> GRIDLLM_MODELS=llama3:8b \
#   GRIDLLM_CHECKPOINT_DIR=/data/checkpoints ./tpu-vm-bootstrap.sh
#
# Multi-host slices (e.g. v5e-16 across 2 hosts): run this on every host;
# jax.distributed coordination is derived from the TPU metadata when
# GRIDLLM_MULTIHOST=1 — only process 0 speaks to the Redis bus (the slice
# registers as ONE logical worker; see gridllm_tpu/parallel/mesh.py).
set -euo pipefail

REPO_DIR=${REPO_DIR:-$(cd "$(dirname "$0")/.." && pwd)}
VENV=${VENV:-$HOME/.gridllm-venv}

if ! command -v python3 >/dev/null; then
  echo "python3 required" >&2; exit 1
fi

python3 -m venv "$VENV" 2>/dev/null || true
source "$VENV/bin/activate"
pip install -q --upgrade pip

# TPU runtime: jax wheel + matching libtpu
pip install -q 'jax[tpu]' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
pip install -q "$REPO_DIR"

python - <<'EOF'
import jax
print("devices:", jax.devices())
assert any(d.platform == "tpu" for d in jax.devices()), "no TPU visible"
EOF

export GRIDLLM_BUS_URL=${GRIDLLM_BUS_URL:-resp://${REDIS_HOST:-localhost}:${REDIS_PORT:-6379}}
export GRIDLLM_MESH_SHAPE=${GRIDLLM_MESH_SHAPE:-tp:-1}

exec gridllm-worker
